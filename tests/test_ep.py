"""Expert-parallel all-to-all dispatch vs the dense single-device oracle."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.parallel.ep import (
    dense_reference_moe,
    make_switch_moe,
    switch_route,
)
from tf_operator_tpu.parallel.mesh import make_mesh

E, D, F = 8, 16, 32
EP = 4


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return (
        jax.random.normal(k1, (E, D, F)) / (D ** 0.5),
        jax.random.normal(k2, (E, F, D)) / (F ** 0.5),
        k3,
    )


def _inputs(key, b=4, s=16):
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (b, s, D))
    logits = jax.random.normal(k2, (b, s, E))
    return x, logits


def test_switch_route_capacity_and_positions():
    logits = jnp.array(
        [[9.0, 0.0], [9.0, 0.0], [9.0, 0.0], [0.0, 9.0]], jnp.float32
    )  # tokens 0,1,2 -> expert 0; token 3 -> expert 1
    dispatch, gate, aux = switch_route(logits, capacity=2)
    # expert 0 takes tokens 0,1 at slots 0,1; token 2 overflows (dropped)
    assert dispatch[0, 0, 0] == 1 and dispatch[1, 0, 1] == 1
    assert dispatch[2].sum() == 0 and gate[2] == 0
    assert dispatch[3, 1, 0] == 1 and gate[3] > 0
    assert aux > 0


def test_all_to_all_matches_dense_no_drops():
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, key = _params(jax.random.PRNGKey(0))
    x, logits = _inputs(jax.random.PRNGKey(1))
    # capacity_factor = E guarantees capacity >= local tokens: nothing drops
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=float(E))
    got, aux = jax.jit(moe)(x, logits, wi, wo)
    want, _ = dense_reference_moe(x, logits, wi, wo, capacity=x.shape[0] * x.shape[1])
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
    assert float(aux) > 0


def test_all_to_all_matches_per_shard_dense_with_drops():
    """With tight capacity, routing is per device shard; the oracle is the
    dense path applied shard-by-shard with the same local capacity."""
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, key = _params(jax.random.PRNGKey(2))
    x, logits = _inputs(jax.random.PRNGKey(3), b=4, s=16)
    factor = 1.0
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=factor)
    got, _ = jax.jit(moe)(x, logits, wi, wo)

    t = x.shape[0] * x.shape[1]
    local = t // EP
    import math

    cap = max(1, math.ceil(local / E * factor))
    xf = x.reshape(t, D)
    lf = logits.reshape(t, E)
    outs = []
    for i in range(EP):
        sl = slice(i * local, (i + 1) * local)
        y, _ = dense_reference_moe(
            xf[sl][None], lf[sl][None], wi, wo, capacity=cap
        )
        outs.append(y[0])
    want = jnp.concatenate(outs).reshape(x.shape)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_gradients_flow_through_all_to_all():
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, _ = _params(jax.random.PRNGKey(4))
    x, logits = _inputs(jax.random.PRNGKey(5))
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=float(E))

    def loss(wi, wo):
        y, aux = moe(x, logits, wi, wo)
        return jnp.sum(y ** 2) + 0.01 * aux

    g_wi, g_wo = jax.jit(jax.grad(loss, argnums=(0, 1)))(wi, wo)

    def loss_ref(wi, wo):
        y, aux = dense_reference_moe(
            x, logits, wi, wo, capacity=x.shape[0] * x.shape[1]
        )
        return jnp.sum(y ** 2) + 0.01 * aux

    r_wi, r_wo = jax.grad(loss_ref, argnums=(0, 1))(wi, wo)
    np.testing.assert_allclose(g_wi, r_wi, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(g_wo, r_wo, atol=1e-4, rtol=1e-4)


def test_transformer_moe_alltoall_matches_dense_dispatch():
    """Model-level EP: a MoE Transformer with moe_dispatch_fn (all-to-all)
    must reproduce the dense-dispatch MoeMlp when capacity is ample."""
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import Transformer, tiny

    mesh = make_mesh({"ep": 4, "dp": 2})
    cfg_dense = tiny(n_experts=4, moe_every=1, dtype=jnp.float32)
    cfg_a2a = tiny(
        n_experts=4, moe_every=1, dtype=jnp.float32,
        moe_dispatch_fn=make_switch_moe(mesh, n_experts=4,
                                        capacity_factor=4.0),
    )
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0, 256)
    m_dense, m_a2a = Transformer(cfg_dense), Transformer(cfg_a2a)
    params = m_dense.init(jax.random.PRNGKey(7), tokens, train=False)["params"]
    want = m_dense.apply({"params": params}, tokens, train=False)
    got = jax.jit(
        lambda p, t: m_a2a.apply({"params": p}, t, train=False)
    )(params, tokens)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_validation_errors():
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    with pytest.raises(ValueError, match="not divisible"):
        make_switch_moe(mesh, n_experts=6)  # 6 % 4 != 0
    # ragged token counts are NOT an error: they pad up to the ep axis
    # (see the ragged tests below)


# ---------------------------------------------------------------- ragged
def test_ragged_tokens_padded_to_ep_axis():
    """Token counts not divisible by ep (the prefill shape) are padded
    internally: with generous capacity the output matches the dense
    oracle on the REAL tokens, and padding contributes nothing."""
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, _ = _params(jax.random.PRNGKey(0))
    # b*s = 3*7 = 21, not divisible by ep=4
    x, logits = _inputs(jax.random.PRNGKey(1), b=3, s=7)
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=float(E))
    got, aux = jax.jit(moe)(x, logits, wi, wo)
    want, _ = dense_reference_moe(x, logits, wi, wo, capacity=21)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
    assert got.shape == x.shape
    assert float(aux) > 0


def test_ragged_aux_excludes_padding():
    """The load-balance statistics must be computed over real tokens
    only: the same tokens with and without forced padding give the same
    aux loss."""
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, _ = _params(jax.random.PRNGKey(2))
    x, logits = _inputs(jax.random.PRNGKey(3), b=4, s=16)  # divisible
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=float(E))
    _, aux_even = jax.jit(moe)(x, logits, wi, wo)
    # same data minus one token: 63 pads to 64 internally; a naive mean
    # over the padded rows would dilute the densities, a masked mean
    # changes aux only by the one missing token's contribution
    x_r = x.reshape(1, 64, -1)[:, :63]
    l_r = logits.reshape(1, 64, -1)[:, :63]
    _, aux_ragged = jax.jit(moe)(x_r, l_r, wi, wo)
    assert abs(float(aux_ragged) - float(aux_even)) < 0.1


def test_switch_route_valid_mask_semantics():
    """Padding rows: no capacity consumed, no dispatch, zero gate,
    excluded from aux."""
    logits = jnp.array(
        [[9.0, 0.0], [9.0, 0.0], [9.0, 0.0], [9.0, 0.0]], jnp.float32)
    valid = jnp.array([True, False, True, True])
    dispatch, gate, aux = switch_route(logits, capacity=2, valid=valid)
    # rows 0, 2 take expert-0 slots 0, 1; row 3 overflows; row 1 (pad)
    # consumed nothing
    assert dispatch[0, 0, 0] == 1 and dispatch[2, 0, 1] == 1
    assert dispatch[1].sum() == 0 and gate[1] == 0
    assert dispatch[3].sum() == 0 and gate[3] == 0  # overflow drop
    # aux densities over the 3 real rows: all routed to expert 0
    assert float(aux) > 0


def test_moe_prefill_generation_under_ep_mesh():
    """Expert-sharded PREFILL (VERDICT r3 weak #6): a mixtral-style tiny
    llama generates under an ep mesh with the all-to-all dispatch doing
    the prefill (batch x prompt_len ragged vs ep), and the tokens match
    the dense-dispatch model exactly."""
    from tf_operator_tpu.models import llama

    mesh = make_mesh({"ep": 4, "dp": 2})
    moe_fn = make_switch_moe(mesh, n_experts=4, capacity_factor=4.0,
                             activation="swiglu")
    base = dict(dtype=jnp.float32, n_experts=4, moe_every=1, max_len=32)
    cfg_dense = llama.tiny(**base)
    cfg_ep = llama.tiny(**base, moe_dispatch_fn=moe_fn)
    # prompt 3 x 5 = 15 tokens — not divisible by ep=4
    prompt = jax.random.randint(jax.random.PRNGKey(0), (3, 5), 0, 256)
    model = llama.Llama(cfg_dense)
    params = model.init(jax.random.PRNGKey(1), prompt, train=False)["params"]
    want = llama.generate(model, params, prompt, max_new_tokens=6)
    with mesh:
        got = llama.generate(
            llama.Llama(cfg_ep), params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ----------------------------------------------------------------- top-k
def test_topk_route_renormalized_gates_and_priority():
    from tf_operator_tpu.parallel.ep import topk_route

    logits = jnp.array(
        [[3.0, 2.0, -9.0], [3.0, 2.0, -9.0], [-9.0, 3.0, 2.0]], jnp.float32)
    dispatch, combine, aux = topk_route(logits, capacity=2, k=2)
    probs = jax.nn.softmax(logits, axis=-1)
    # token 0: experts 0,1 with gates p0/(p0+p1), p1/(p0+p1)
    g0 = float(probs[0, 0] / (probs[0, 0] + probs[0, 1]))
    np.testing.assert_allclose(float(combine[0, 0].sum()), g0, rtol=1e-6)
    np.testing.assert_allclose(float(combine[0, 1].sum()), 1 - g0, rtol=1e-6)
    # combine weights sum to 1 for tokens with both choices live; token 1
    # loses its SECOND choice to capacity (expert 1 full) and keeps only
    # its first-choice gate g0 — the drop sheds gate weight, not tokens
    np.testing.assert_allclose(
        np.asarray(combine.sum(axis=(1, 2))),
        np.array([1.0, g0, 1.0]), rtol=1e-5)
    # first-choice priority: expert 1 is claimed FIRST-choice by token 2
    # and second-choice by tokens 0, 1 -> with capacity 2, token 2's
    # first choice must survive; one of the second choices drops
    d1 = np.asarray(dispatch[:, 1].sum(axis=-1))  # per-token use of e1
    assert d1[2] == 1, "first-choice claim was shed before second choices"
    assert d1.sum() == 2  # capacity bound respected
    assert float(aux) > 0


def test_dense_dispatch_top2_matches_manual_reference():
    from tf_operator_tpu.parallel.ep import dense_switch_dispatch

    wi, wo, _ = _params(jax.random.PRNGKey(4))
    x, logits = _inputs(jax.random.PRNGKey(5), b=2, s=8)
    got, aux = dense_switch_dispatch(x, logits, wi, wo, top_k=2)
    # manual: run every expert densely, weight by renormalized top-2 gates
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, 2)
    gates = top_p / top_p.sum(-1, keepdims=True)
    h = jnp.einsum("bsd,edf->bsef", x, wi)
    h = jax.nn.gelu(h)
    full = jnp.einsum("bsef,efd->bsed", h, wo)
    want = sum(
        jnp.take_along_axis(
            full, top_i[..., c, None, None], axis=2
        )[:, :, 0] * gates[..., c, None]
        for c in range(2)
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    assert float(aux) > 0


def test_all_to_all_top2_matches_dense_reference():
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, _ = _params(jax.random.PRNGKey(6))
    x, logits = _inputs(jax.random.PRNGKey(7))
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=float(E),
                          top_k=2)
    got, aux = jax.jit(moe)(x, logits, wi, wo)
    want, _ = dense_reference_moe(
        x, logits, wi, wo, capacity=2 * x.shape[0] * x.shape[1], top_k=2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5, rtol=1e-5)
    assert float(aux) > 0


def test_mixtral_top2_decode_matches_forward():
    """True-Mixtral tiny llama (top-2, renormalized gates): the decode
    gather path (k experts per step) must reproduce the dense forward
    logits position by position."""
    from tf_operator_tpu.models import llama

    cfg = llama.tiny(dtype=jnp.float32, n_experts=4, moe_every=1,
                     moe_top_k=2, max_len=32)
    toks = jax.random.randint(jax.random.PRNGKey(0), (2, 12), 0, 256)
    model = llama.Llama(cfg)
    params = model.init(jax.random.PRNGKey(1), toks, train=False)["params"]
    full = model.apply({"params": params}, toks)  # [B, S, V]
    cache = llama.init_cache(cfg, 2)
    # prefill the first 4, then decode one token at a time
    logits, cache = model.apply(
        {"params": params}, toks[:, :4], cache=cache, cache_pos=0)
    np.testing.assert_allclose(np.asarray(logits[:, -1]),
                               np.asarray(full[:, 3]), atol=2e-4, rtol=2e-4)
    for i in range(4, 12):
        logits, cache = model.apply(
            {"params": params}, toks[:, i:i + 1], cache=cache, cache_pos=i)
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(full[:, i]),
            atol=2e-4, rtol=2e-4, err_msg=f"pos {i}")


def test_moe_top_k_dispatch_fn_mismatch_rejected():
    """One generate() must never mix top-1 prefill with top-2 decode:
    a dispatch fn built with a different top_k than the config refuses
    at config construction."""
    from tf_operator_tpu.models import llama

    mesh = make_mesh({"ep": 4, "dp": 2})
    fn1 = make_switch_moe(mesh, n_experts=4, top_k=1)
    with pytest.raises(ValueError, match="top-1.*moe_top_k=2"):
        llama.tiny(n_experts=4, moe_every=1, moe_top_k=2,
                   moe_dispatch_fn=fn1)
    # matching arity constructs fine
    fn2 = make_switch_moe(mesh, n_experts=4, top_k=2)
    llama.tiny(n_experts=4, moe_every=1, moe_top_k=2, moe_dispatch_fn=fn2)


def test_mixtral_top2_prefill_under_ep_matches_dense():
    """True-Mixtral (top-2) expert-sharded prefill: generation under the
    ep mesh with a top-2 dispatch fn equals the dense top-2 model."""
    from tf_operator_tpu.models import llama

    mesh = make_mesh({"ep": 4, "dp": 2})
    moe_fn = make_switch_moe(mesh, n_experts=4, capacity_factor=4.0,
                             activation="swiglu", top_k=2)
    base = dict(dtype=jnp.float32, n_experts=4, moe_every=1, moe_top_k=2,
                max_len=32)
    prompt = jax.random.randint(jax.random.PRNGKey(4), (3, 5), 0, 256)
    model = llama.Llama(llama.tiny(**base))
    params = model.init(jax.random.PRNGKey(5), prompt, train=False)["params"]
    want = llama.generate(model, params, prompt, max_new_tokens=6)
    with mesh:
        got = llama.generate(
            llama.Llama(llama.tiny(**base, moe_dispatch_fn=moe_fn)),
            params, prompt, max_new_tokens=6)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
