"""Expert-parallel all-to-all dispatch vs the dense single-device oracle."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.parallel.ep import (
    dense_reference_moe,
    make_switch_moe,
    switch_route,
)
from tf_operator_tpu.parallel.mesh import make_mesh

E, D, F = 8, 16, 32
EP = 4


def _params(key):
    k1, k2, k3 = jax.random.split(key, 3)
    return (
        jax.random.normal(k1, (E, D, F)) / (D ** 0.5),
        jax.random.normal(k2, (E, F, D)) / (F ** 0.5),
        k3,
    )


def _inputs(key, b=4, s=16):
    k1, k2 = jax.random.split(key)
    x = jax.random.normal(k1, (b, s, D))
    logits = jax.random.normal(k2, (b, s, E))
    return x, logits


def test_switch_route_capacity_and_positions():
    logits = jnp.array(
        [[9.0, 0.0], [9.0, 0.0], [9.0, 0.0], [0.0, 9.0]], jnp.float32
    )  # tokens 0,1,2 -> expert 0; token 3 -> expert 1
    dispatch, gate, aux = switch_route(logits, capacity=2)
    # expert 0 takes tokens 0,1 at slots 0,1; token 2 overflows (dropped)
    assert dispatch[0, 0, 0] == 1 and dispatch[1, 0, 1] == 1
    assert dispatch[2].sum() == 0 and gate[2] == 0
    assert dispatch[3, 1, 0] == 1 and gate[3] > 0
    assert aux > 0


def test_all_to_all_matches_dense_no_drops():
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, key = _params(jax.random.PRNGKey(0))
    x, logits = _inputs(jax.random.PRNGKey(1))
    # capacity_factor = E guarantees capacity >= local tokens: nothing drops
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=float(E))
    got, aux = jax.jit(moe)(x, logits, wi, wo)
    want, _ = dense_reference_moe(x, logits, wi, wo, capacity=x.shape[0] * x.shape[1])
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)
    assert float(aux) > 0


def test_all_to_all_matches_per_shard_dense_with_drops():
    """With tight capacity, routing is per device shard; the oracle is the
    dense path applied shard-by-shard with the same local capacity."""
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, key = _params(jax.random.PRNGKey(2))
    x, logits = _inputs(jax.random.PRNGKey(3), b=4, s=16)
    factor = 1.0
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=factor)
    got, _ = jax.jit(moe)(x, logits, wi, wo)

    t = x.shape[0] * x.shape[1]
    local = t // EP
    import math

    cap = max(1, math.ceil(local / E * factor))
    xf = x.reshape(t, D)
    lf = logits.reshape(t, E)
    outs = []
    for i in range(EP):
        sl = slice(i * local, (i + 1) * local)
        y, _ = dense_reference_moe(
            xf[sl][None], lf[sl][None], wi, wo, capacity=cap
        )
        outs.append(y[0])
    want = jnp.concatenate(outs).reshape(x.shape)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_gradients_flow_through_all_to_all():
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    wi, wo, _ = _params(jax.random.PRNGKey(4))
    x, logits = _inputs(jax.random.PRNGKey(5))
    moe = make_switch_moe(mesh, n_experts=E, capacity_factor=float(E))

    def loss(wi, wo):
        y, aux = moe(x, logits, wi, wo)
        return jnp.sum(y ** 2) + 0.01 * aux

    g_wi, g_wo = jax.jit(jax.grad(loss, argnums=(0, 1)))(wi, wo)

    def loss_ref(wi, wo):
        y, aux = dense_reference_moe(
            x, logits, wi, wo, capacity=x.shape[0] * x.shape[1]
        )
        return jnp.sum(y ** 2) + 0.01 * aux

    r_wi, r_wo = jax.grad(loss_ref, argnums=(0, 1))(wi, wo)
    np.testing.assert_allclose(g_wi, r_wi, atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(g_wo, r_wo, atol=1e-4, rtol=1e-4)


def test_transformer_moe_alltoall_matches_dense_dispatch():
    """Model-level EP: a MoE Transformer with moe_dispatch_fn (all-to-all)
    must reproduce the dense-dispatch MoeMlp when capacity is ample."""
    import jax.numpy as jnp

    from tf_operator_tpu.models.transformer import Transformer, tiny

    mesh = make_mesh({"ep": 4, "dp": 2})
    cfg_dense = tiny(n_experts=4, moe_every=1, dtype=jnp.float32)
    cfg_a2a = tiny(
        n_experts=4, moe_every=1, dtype=jnp.float32,
        moe_dispatch_fn=make_switch_moe(mesh, n_experts=4,
                                        capacity_factor=4.0),
    )
    tokens = jax.random.randint(jax.random.PRNGKey(6), (4, 16), 0, 256)
    m_dense, m_a2a = Transformer(cfg_dense), Transformer(cfg_a2a)
    params = m_dense.init(jax.random.PRNGKey(7), tokens, train=False)["params"]
    want = m_dense.apply({"params": params}, tokens, train=False)
    got = jax.jit(
        lambda p, t: m_a2a.apply({"params": p}, t, train=False)
    )(params, tokens)
    np.testing.assert_allclose(got, want, atol=1e-4, rtol=1e-4)


def test_validation_errors():
    mesh = make_mesh({"ep": EP, "dp": 8 // EP})
    with pytest.raises(ValueError, match="not divisible"):
        make_switch_moe(mesh, n_experts=6)  # 6 % 4 != 0
    moe = make_switch_moe(mesh, n_experts=E)
    x = jnp.zeros((1, 6, D))  # 6 tokens, not divisible by ep=4
    with pytest.raises(ValueError, match="tokens"):
        moe(x, jnp.zeros((1, 6, E)), jnp.zeros((E, D, F)), jnp.zeros((E, F, D)))
