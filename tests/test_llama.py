"""LLaMA-family model (models/llama.py): RoPE properties, GQA vs a dense
reference, flash/ring attention drop-in parity, and tp/fsdp/dp sharded
train-step parity against the unsharded run."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tf_operator_tpu.models import llama
from tf_operator_tpu.models.transformer import lm_loss
from tf_operator_tpu.parallel.mesh import make_mesh
from tf_operator_tpu.parallel.tp import state_sharding
from tf_operator_tpu.runtime.train import create_train_state


def _f32(**kw):
    kw.setdefault("dtype", jnp.float32)
    return llama.tiny(**kw)


def _tokens(cfg, batch=2, seed=0):
    return jax.random.randint(
        jax.random.PRNGKey(seed), (batch, cfg.max_len), 0, cfg.vocab_size
    )


# ------------------------------------------------------------------ rotary
def test_rope_preserves_norm():
    angles = llama.rope_table(16, 8, 10000.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 16, 4, 8))
    rx = llama.apply_rope(x, angles)
    assert rx.shape == x.shape
    assert jnp.allclose(
        jnp.linalg.norm(rx, axis=-1), jnp.linalg.norm(x, axis=-1), atol=1e-5
    )


def test_rope_scores_depend_on_relative_position_only():
    """<R(p)q, R(p+d)k> must equal <R(p')q, R(p'+d)k> for any base p, p'."""
    head_dim, delta = 8, 3
    table = llama.rope_table(64, head_dim, 10000.0)
    q = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 1, head_dim))
    k = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 1, head_dim))

    def score(base):
        rq = llama.apply_rope(q, table[base: base + 1])
        rk = llama.apply_rope(k, table[base + delta: base + delta + 1])
        return float(jnp.sum(rq * rk))

    assert abs(score(0) - score(17)) < 1e-4
    assert abs(score(5) - score(40)) < 1e-4


def test_rope_position_zero_is_identity():
    table = llama.rope_table(4, 8, 10000.0)
    x = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 2, 8))
    assert jnp.allclose(llama.apply_rope(x, table[:1]), x, atol=1e-6)


def test_explicit_positions_match_default():
    cfg = _f32()
    model = llama.Llama(cfg)
    toks = _tokens(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    base = model.apply({"params": params}, toks)
    pos = jnp.arange(cfg.max_len)
    explicit = model.apply({"params": params}, toks, positions=pos)
    assert jnp.allclose(base, explicit, atol=1e-6)


# -------------------------------------------------------------------- gqa
def _dense_gqa_reference(q, k, v):
    """Per-head causal attention with each kv head explicitly indexed by
    its query group — independent math to check the broadcast path."""
    b, s, h, d = q.shape
    kv = k.shape[2]
    group = h // kv
    outs = []
    for head in range(h):
        qi = q[:, :, head].astype(jnp.float32)
        ki = k[:, :, head // group].astype(jnp.float32)
        vi = v[:, :, head // group].astype(jnp.float32)
        scores = qi @ ki.transpose(0, 2, 1) / jnp.sqrt(d)
        mask = jnp.tril(jnp.ones((s, s), bool))
        scores = jnp.where(mask, scores, -1e30)
        outs.append(jax.nn.softmax(scores, axis=-1) @ vi)
    return jnp.stack(outs, axis=2)


def test_gqa_broadcast_matches_dense_reference():
    b, s, h, kv, d = 2, 8, 4, 2, 6
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, kv, d))
    v = jax.random.normal(ks[2], (b, s, kv, d))
    ref = _dense_gqa_reference(q, k, v)
    from tf_operator_tpu.models.transformer import dot_product_attention

    got = dot_product_attention(
        q, jnp.repeat(k, h // kv, axis=2), jnp.repeat(v, h // kv, axis=2), True
    )
    assert jnp.allclose(got, ref, atol=1e-5), float(jnp.abs(got - ref).max())


def test_mha_config_is_gqa_with_group_one():
    """n_kv_heads == n_heads must behave as plain MHA (group size 1 path)."""
    cfg = _f32(n_kv_heads=4)
    assert cfg.q_per_kv == 1
    model = llama.Llama(cfg)
    toks = _tokens(cfg)
    logits = model.init_with_output(
        jax.random.PRNGKey(0), toks, train=False
    )[0]
    assert logits.shape == (*toks.shape, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_param_shapes_and_flops():
    cfg = llama.tiny()
    model = llama.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), _tokens(cfg), train=False
    )["params"]
    blk = params["block0"]
    assert blk["attn"]["wq"]["kernel"].shape == (64, 4, 16)
    assert blk["attn"]["wkv"]["kernel"].shape == (64, 2, 2, 16)
    assert blk["attn"]["out"]["kernel"].shape == (4, 16, 64)
    assert blk["mlp"]["wi"]["kernel"].shape == (64, 2, 128)
    assert blk["mlp"]["wo"]["kernel"].shape == (128, 64)
    n_params = sum(x.size for x in jax.tree.leaves(params))
    # flops accounting covers matmul params (excludes rmsnorm scales);
    # untied lm_head doubles the embed term relative to the tied count
    approx = llama.params_flops_per_token(cfg) / 6.0
    approx += cfg.vocab_size * cfg.d_model  # lm_head (untied default)
    assert abs(n_params - approx) / n_params < 0.01


def test_factory_configs_validate():
    assert llama.llama_7b().q_per_kv == 1
    assert llama.llama3_8b().q_per_kv == 4
    with pytest.raises(ValueError):
        llama.tiny(n_kv_heads=3)
    with pytest.raises(ValueError):
        llama.tiny(d_model=65)


# ------------------------------------------------------------ attention fns
def test_flash_attention_drop_in_parity():
    from tf_operator_tpu.ops.flash_attention import flash_attention

    cfg = _f32(max_len=256)
    toks = _tokens(cfg)
    model = llama.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    ref = model.apply({"params": params}, toks)
    flash_model = llama.Llama(
        llama.tiny(dtype=jnp.float32, max_len=256, attention_fn=flash_attention)
    )
    got = flash_model.apply({"params": params}, toks)
    assert jnp.allclose(got, ref, atol=2e-3), float(jnp.abs(got - ref).max())


def test_ring_attention_drop_in_parity():
    """Ring attention over tp=2 (sequence parallel) on the sharded model
    must match the single-device einsum run."""
    devices = jax.devices()[:2]
    mesh = make_mesh({"tp": 2}, devices=devices)
    from tf_operator_tpu.ops.ring_attention import make_ring_attention_fn

    cfg = _f32()
    toks = _tokens(cfg)
    model = llama.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    ref = model.apply({"params": params}, toks)
    ring_model = llama.Llama(
        _f32(attention_fn=make_ring_attention_fn(mesh, axis_name="tp"))
    )
    with mesh:
        got = jax.jit(
            lambda p, t: ring_model.apply({"params": p}, t)
        )(params, toks)
    assert jnp.allclose(got, ref, atol=2e-3), float(jnp.abs(got - ref).max())


# --------------------------------------------------------------- sharding
def test_tp_fsdp_dp_train_step_parity():
    """One adam step over a tp=2 x fsdp=2 x dp=2 mesh must match the
    unsharded single-device step (loss + grad global norm)."""
    devices = jax.devices()
    assert len(devices) >= 8, "conftest forces an 8-device CPU mesh"
    mesh = make_mesh({"tp": 2, "fsdp": 2, "dp": 2}, devices=devices[:8])
    mesh1 = make_mesh({}, devices=devices[:1])
    cfg = _f32()
    model = llama.Llama(cfg)
    toks = _tokens(cfg, batch=8)

    def one_step(m):
        from jax.sharding import NamedSharding, PartitionSpec as P

        rng = jax.random.PRNGKey(0)
        state = create_train_state(rng, model, toks, optax.adam(1e-3))
        st_sh = state_sharding(state, m)
        state = jax.device_put(state, st_sh)
        batch_sh = NamedSharding(m, P(("dp", "fsdp"), None))
        t = jax.device_put(toks, batch_sh)

        def train_step(state, t):
            def loss_fn(p):
                return lm_loss(model.apply({"params": p}, t), t)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads), loss, optax.global_norm(grads)

        step = jax.jit(
            train_step, in_shardings=(st_sh, batch_sh), donate_argnums=(0,)
        )
        state, loss, gnorm = step(state, t)
        return float(loss), float(gnorm)

    loss, gnorm = one_step(mesh)
    loss1, gnorm1 = one_step(mesh1)
    assert abs(loss - loss1) / abs(loss1) < 1e-4, (loss, loss1)
    assert abs(gnorm - gnorm1) / abs(gnorm1) < 1e-3, (gnorm, gnorm1)


def test_tp_shards_llama_params():
    mesh = make_mesh({"tp": 2, "fsdp": 2, "dp": 2}, devices=jax.devices()[:8])
    cfg = llama.tiny(d_ff=256)
    model = llama.Llama(cfg)
    params = model.init(
        jax.random.PRNGKey(0), _tokens(cfg), train=False
    )["params"]
    from tf_operator_tpu.parallel.tp import transformer_param_sharding

    sh = transformer_param_sharding(params, mesh, min_fsdp_size=0)
    blk = sh["block0"]
    assert "tp" in blk["attn"]["wq"]["kernel"].spec
    assert blk["attn"]["wq"]["kernel"].spec[1] == "tp"
    assert blk["attn"]["wkv"]["kernel"].spec[2] == "tp"
    assert blk["attn"]["out"]["kernel"].spec[0] == "tp"
    assert blk["mlp"]["wi"]["kernel"].spec[2] == "tp"
    assert blk["mlp"]["wo"]["kernel"].spec[0] == "tp"


# ------------------------------------------------------------- blocked CE
def test_blocked_ce_hidden_seam():
    """return_hidden + tied embedding + blocked CE == full-logits loss."""
    from tf_operator_tpu.ops.blocked_ce import blocked_cross_entropy

    cfg = _f32(tie_embeddings=True)
    model = llama.Llama(cfg)
    toks = _tokens(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    full = lm_loss(model.apply({"params": params}, toks), toks)
    hidden = model.apply({"params": params}, toks, return_hidden=True)
    w = params["embed"]["embedding"].T.astype(jnp.float32)
    x = hidden[:, :-1].reshape(-1, cfg.d_model).astype(jnp.float32)
    labels = toks[:, 1:].reshape(-1)
    blocked = blocked_cross_entropy(x, w, labels, chunk=128)
    assert abs(float(full) - float(blocked)) < 1e-5


def test_bench_llama_path_runs_on_tiny_config():
    """bench.bench_llama's stack (bf16 params + adafactor + remat + GQA +
    blocked CE over the tied embedding) must execute end to end; the real
    run only swaps in the 1B-class config."""
    import bench  # repo root is on sys.path via tests/conftest.py

    cfg = llama.tiny(tie_embeddings=True, remat=True)
    r = bench.bench_llama("cpu", cfg=cfg)
    assert r["tokens_per_sec_per_chip"] > 0
    assert r["loss_after_warmup"] > 0
    assert r["gqa"] == "4q:2kv"


# ---------------------------------------------------- GQA-native flash
def _flash_gqa_case(causal, s=256, b=2, h=4, kv=2, d=8, seed=0):
    from tf_operator_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (b, s, h, d))
    k = jax.random.normal(ks[1], (b, s, kv, d))
    v = jax.random.normal(ks[2], (b, s, kv, d))

    def flash_loss(q, k, v):
        out = flash_attention(q, k, v, causal, blk_q=128, blk_k=128)
        return jnp.sum(out * out), out

    def ref_loss(q, k, v):
        from tf_operator_tpu.models.transformer import dot_product_attention

        g = h // kv
        out = dot_product_attention(
            q, jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2), causal
        )
        return jnp.sum(out * out), out

    (_, out_f), gf = jax.value_and_grad(flash_loss, argnums=(0, 1, 2),
                                        has_aux=True)(q, k, v)
    (_, out_r), gr = jax.value_and_grad(ref_loss, argnums=(0, 1, 2),
                                        has_aux=True)(q, k, v)
    return out_f, gf, out_r, gr


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gqa_kernel_matches_reference(causal):
    """The GQA-native kernel (kv heads indexed via BlockSpec maps, dk/dv
    accumulated over the query group) must match the repeat+dense path
    forward AND backward — including the kv-shaped [B,S,KV,D] grads."""
    out_f, gf, out_r, gr = _flash_gqa_case(causal)
    assert out_f.shape == out_r.shape
    assert jnp.allclose(out_f, out_r, atol=2e-5), float(
        jnp.abs(out_f - out_r).max()
    )
    for a, b_, name in zip(gf, gr, "qkv"):
        assert a.shape == b_.shape, name
        assert jnp.allclose(a, b_, atol=5e-5), (
            name, float(jnp.abs(a - b_).max())
        )


def test_flash_gqa_kv_grad_shapes():
    """dk/dv must come back in the compact [B,S,KV,D] shape (not the
    broadcast H shape) so the wkv projection grad math stays compact."""
    out_f, gf, _, _ = _flash_gqa_case(True, kv=1)  # MQA extreme
    assert gf[0].shape == (2, 256, 4, 8)
    assert gf[1].shape == (2, 256, 1, 8)
    assert gf[2].shape == (2, 256, 1, 8)


def test_flash_gqa_rejects_indivisible_heads():
    from tf_operator_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros((1, 128, 4, 8))
    kv = jnp.zeros((1, 128, 3, 8))
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, kv, kv, True)


def test_llama_flash_skips_repeat_and_matches_einsum():
    """End to end: the GQA llama with flash attention (no kv broadcast)
    must match the einsum path (which broadcasts)."""
    from tf_operator_tpu.ops.flash_attention import flash_attention

    assert flash_attention.supports_gqa
    cfg = _f32(max_len=256)
    assert cfg.q_per_kv == 2
    toks = _tokens(cfg)
    model = llama.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    ref = model.apply({"params": params}, toks)
    flash_model = llama.Llama(
        _f32(max_len=256, attention_fn=flash_attention)
    )
    got = flash_model.apply({"params": params}, toks)
    assert jnp.allclose(got, ref, atol=2e-3), float(jnp.abs(got - ref).max())


def test_flash_gqa_rejects_mismatched_kv_shapes():
    from tf_operator_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros((1, 128, 4, 8))
    k = jnp.zeros((1, 128, 2, 8))
    v = jnp.zeros((1, 128, 4, 8))  # half-migrated caller: broadcast v
    with pytest.raises(ValueError, match="must match"):
        flash_attention(q, k, v, True)


# ----------------------------------------------------------- generation
def _naive_greedy(model, params, prompt, n):
    """Oracle: re-run the FULL forward over the growing sequence each
    step and take argmax of the last position."""
    toks = prompt
    out = []
    for _ in range(n):
        logits = model.apply({"params": params}, toks)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


def test_greedy_generate_matches_full_forward_oracle():
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :8]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    want = _naive_greedy(model, params, prompt, 6)
    got = llama.generate(model, params, prompt, 6)
    assert got.shape == (2, 6)
    assert jnp.array_equal(got, want), (got, want)


def test_prefill_logits_match_full_forward():
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :10]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    full = model.apply({"params": params}, prompt)
    cache = llama.init_cache(cfg, 2)
    dec, new_cache = model.apply(
        {"params": params}, prompt, cache=cache, cache_pos=0)
    assert jnp.allclose(dec, full, atol=1e-4), float(jnp.abs(dec - full).max())
    assert len(new_cache) == cfg.n_layers


def test_cache_is_compact_kv():
    cfg = llama.tiny()  # 4 q heads, 2 kv heads
    cache = llama.init_cache(cfg, batch=3, cache_len=32)
    k, v = cache[0]
    assert k.shape == (3, 32, 2, 16)
    assert v.shape == (3, 32, 2, 16)


def test_generate_single_token():
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=1)[:, :4]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    got = llama.generate(model, params, prompt, 1)
    want = _naive_greedy(model, params, prompt, 1)
    assert jnp.array_equal(got, want)


def test_generate_sampling_runs_and_respects_cache_bound():
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :4]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    out = llama.generate(model, params, prompt, 5,
                         rng=jax.random.PRNGKey(7), temperature=0.8)
    assert out.shape == (2, 5)
    assert bool((out >= 0).all()) and bool((out < cfg.vocab_size).all())
    with pytest.raises(ValueError, match="exceeds"):
        llama.generate(model, params, prompt, cfg.max_len)
    with pytest.raises(ValueError, match="needs an rng"):
        llama.generate(model, params, prompt, 2, temperature=1.0)


def test_generate_zero_tokens_and_bad_cache_len():
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :4]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    out = llama.generate(model, params, prompt, 0)
    assert out.shape == (2, 0)
    with pytest.raises(ValueError, match=">= 0"):
        llama.generate(model, params, prompt, -1)
    # cache longer than the RoPE table must be rejected, not silently
    # decoded with clamped rotations
    with pytest.raises(ValueError, match="max_len"):
        llama.init_cache(cfg, 2, cache_len=cfg.max_len * 2)


def test_generate_reuses_compiled_fns():
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :4]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    llama.generate(model, params, prompt, 2)
    fns = llama._decode_fns(model, 0.0)
    before = llama._decode_fns_cached.cache_info().hits
    llama.generate(model, params, prompt, 2)
    assert llama._decode_fns_cached.cache_info().hits > before
    # an equal-config model instance shares the cache entry
    assert llama._decode_fns(llama.Llama(cfg), 0.0) is fns


# ------------------------------------------------------------------ MoE
def test_moe_llama_trains_and_collects_aux():
    from tf_operator_tpu.models.transformer import apply_with_aux

    cfg = _f32(n_experts=4, moe_every=2)
    model = llama.Llama(cfg)
    toks = _tokens(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    # experts only in every 2nd block; swiglu experts pack gate+up
    assert "moe" in params["block1"] and "mlp" in params["block0"]
    assert params["block1"]["moe"]["wi"].shape == (4, 64, 256)
    assert params["block1"]["moe"]["wo"].shape == (4, 128, 64)
    logits, aux = apply_with_aux(model, params, toks)
    assert jnp.isfinite(logits).all()
    assert float(aux) > 0.0  # load-balance loss collected via sow


def test_moe_llama_ep_dispatch_matches_dense_reference():
    """All-to-all SwiGLU experts over an ep mesh == the dense masked
    dispatch (capacity = tokens so nothing drops)."""
    from tf_operator_tpu.models.transformer import apply_with_aux
    from tf_operator_tpu.parallel.ep import make_switch_moe

    mesh = make_mesh({"ep": 2, "dp": 4}, devices=jax.devices()[:8])
    n_e = 4
    dense_cfg = _f32(n_experts=n_e, moe_every=2)
    dispatch = make_switch_moe(mesh, n_e, capacity_factor=float(n_e),
                               activation="swiglu")
    ep_cfg = _f32(n_experts=n_e, moe_every=2, moe_dispatch_fn=dispatch)
    toks = _tokens(cfg=dense_cfg, batch=4)
    dense_model = llama.Llama(dense_cfg)
    params = dense_model.init(
        jax.random.PRNGKey(0), toks, train=False)["params"]
    want, aux_d = apply_with_aux(dense_model, params, toks)
    with mesh:
        got, aux_e = jax.jit(
            lambda p, t: apply_with_aux(llama.Llama(ep_cfg), p, t)
        )(params, toks)
    assert jnp.allclose(got, want, atol=2e-3), float(jnp.abs(got - want).max())
    # aux is a pmean of per-shard stats — looser (see __graft_entry__)
    assert abs(float(aux_e) - float(aux_d)) / abs(float(aux_d)) < 0.3


def test_moe_llama_decode_matches_full_forward():
    """Generation with MoE blocks: cached decode logits == full forward."""
    cfg = _f32(n_experts=4, moe_every=2)
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :8]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    full = model.apply({"params": params}, prompt)
    cache = llama.init_cache(cfg, 2)
    dec, _ = model.apply({"params": params}, prompt, cache=cache, cache_pos=0)
    assert jnp.allclose(dec, full, atol=1e-4), float(jnp.abs(dec - full).max())


def test_mixtral_factory():
    cfg = llama.mixtral_8x7b()
    assert cfg.n_experts == 8 and cfg.moe_every == 1
    assert cfg.q_per_kv == 4


def test_moe_llama_decode_with_ep_dispatch_falls_back_dense():
    """A model built with the all-to-all dispatch must still decode: the
    cache path forces dense routing (single-token steps can't satisfy
    the dispatch's token divisibility and don't need its collectives)."""
    from tf_operator_tpu.parallel.ep import make_switch_moe

    mesh = make_mesh({"ep": 2, "dp": 4}, devices=jax.devices()[:8])
    dispatch = make_switch_moe(mesh, 4, capacity_factor=4.0,
                               activation="swiglu")
    cfg = _f32(n_experts=4, moe_every=2, moe_dispatch_fn=dispatch)
    model = llama.Llama(cfg)
    # init takes the training path: its sample must satisfy the dispatch's
    # token divisibility (4 % ep == 0); decode afterwards may use ANY
    # prompt length (5 here) because the cache path routes densely
    init_toks = _tokens(cfg, batch=1)[:, :4]
    params = model.init(
        jax.random.PRNGKey(0), init_toks, train=False)["params"]
    prompt = _tokens(cfg, batch=1)[:, :5]
    out = llama.generate(model, params, prompt, 3)
    assert out.shape == (1, 3)


# --------------------------------------------------------- sliding window
def _band_reference(q, k, v, window):
    """Independent banded-causal oracle (per-head loops, explicit mask)."""
    b, s, h, d = q.shape
    outs = []
    for head in range(h):
        qi = q[:, :, head].astype(jnp.float32)
        ki = k[:, :, head].astype(jnp.float32)
        vi = v[:, :, head].astype(jnp.float32)
        scores = qi @ ki.transpose(0, 2, 1) / jnp.sqrt(d)
        ids = jnp.arange(s)
        mask = (ids[:, None] >= ids[None, :]) & (
            ids[None, :] > ids[:, None] - window)
        scores = jnp.where(mask, scores, -1e30)
        outs.append(jax.nn.softmax(scores, axis=-1) @ vi)
    return jnp.stack(outs, axis=2)


@pytest.mark.parametrize("window", [32, 100, 256])
def test_flash_sliding_window_matches_band_oracle(window):
    from tf_operator_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(x, (1, 256, 2, 8)) for x in ks)
    got = flash_attention(q, k, v, True, window=window,
                          blk_q=64, blk_k=64)
    want = _band_reference(q, k, v, window)
    assert jnp.allclose(got, want, atol=2e-5), float(jnp.abs(got - want).max())


def test_flash_sliding_window_grads_match_einsum():
    from tf_operator_tpu.models.transformer import dot_product_attention
    from tf_operator_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q, k, v = (jax.random.normal(x, (1, 256, 2, 8)) for x in ks)

    def loss(f):
        return lambda q, k, v: jnp.sum(f(q, k, v) ** 2)

    gf = jax.grad(loss(lambda *a: flash_attention(
        *a, True, window=64, blk_q=64, blk_k=64)), argnums=(0, 1, 2))(q, k, v)
    gw = jax.grad(loss(lambda *a: dot_product_attention(
        *a, True, window=64)), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gf, gw, "qkv"):
        assert jnp.allclose(a, b, atol=5e-5), (
            name, float(jnp.abs(a - b).max()))


def test_flash_window_gqa_composes():
    """Sliding window + compact GQA kv through the kernel together."""
    from tf_operator_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(2), 3)
    q = jax.random.normal(ks[0], (1, 256, 4, 8))
    k = jax.random.normal(ks[1], (1, 256, 2, 8))
    v = jax.random.normal(ks[2], (1, 256, 2, 8))
    got = flash_attention(q, k, v, True, window=64, blk_q=64, blk_k=64)
    g = 2
    want = _band_reference(
        q, jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2), 64)
    assert jnp.allclose(got, want, atol=2e-5), float(jnp.abs(got - want).max())


def test_flash_window_validation():
    from tf_operator_tpu.ops.flash_attention import flash_attention

    q = jnp.zeros((1, 128, 2, 8))
    with pytest.raises(ValueError, match="causal"):
        flash_attention(q, q, q, False, window=32)
    with pytest.raises(ValueError, match=">= 1"):
        flash_attention(q, q, q, True, window=0)


def test_sliding_window_model_decode_matches_full_forward():
    """A mistral-style config (window < seq len) must produce identical
    logits through the training path and the cached decode path."""
    cfg = _f32(sliding_window=10)
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :24]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    full = model.apply({"params": params}, prompt)
    cache = llama.init_cache(cfg, 2)
    dec, _ = model.apply({"params": params}, prompt, cache=cache, cache_pos=0)
    assert jnp.allclose(dec, full, atol=1e-4), float(jnp.abs(dec - full).max())


def test_sliding_window_changes_output_vs_full_causal():
    """The window must actually bite: long-range attention differs."""
    cfg_full = _f32()
    cfg_win = _f32(sliding_window=4)
    model_f, model_w = llama.Llama(cfg_full), llama.Llama(cfg_win)
    toks = _tokens(cfg_full)
    params = model_f.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    lf = model_f.apply({"params": params}, toks)
    lw = model_w.apply({"params": params}, toks)
    # early positions (inside the window) agree; late positions diverge
    assert jnp.allclose(lf[:, :4], lw[:, :4], atol=1e-5)
    assert not jnp.allclose(lf[:, -1], lw[:, -1], atol=1e-3)


def test_mistral_factory():
    cfg = llama.mistral_7b()
    assert cfg.sliding_window == 4096 and cfg.q_per_kv == 4


def test_supports_gqa_looks_through_partial():
    import functools

    from tf_operator_tpu.ops.flash_attention import flash_attention

    wrapped = functools.partial(flash_attention, blk_q=64, blk_k=64)
    assert llama._supports_gqa(wrapped)
    assert llama._supports_gqa(flash_attention)
    assert not llama._supports_gqa(lambda q, k, v, c: q)


def test_rolling_cache_windowed_decode_matches_oracle():
    """With sliding_window set, generate() sizes the cache to the window
    (ring buffer) — greedy tokens must still match the naive oracle that
    re-runs the full windowed forward each step, INCLUDING past the
    point where the ring wraps and overwrites old slots."""
    cfg = _f32(max_len=64, sliding_window=8)
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :10]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    n = 30  # prompt 10 + 30 = 40 positions through a 128-slot... ensure ring
    # force a tight ring: cache_len = 16 (>= window 8, < total 40)
    got = llama.generate(model, params, prompt, n, cache_len=16)
    want = _naive_greedy(model, params, prompt, n)
    assert jnp.array_equal(got, want), (got[0].tolist(), want[0].tolist())


def test_rolling_cache_rejects_undersized_ring():
    cfg = _f32(max_len=64, sliding_window=16)
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=1)[:, :4]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    with pytest.raises(ValueError, match="visible positions"):
        llama.generate(model, params, prompt, 20, cache_len=8)
    # prompt longer than the ring: prefill would wrap
    long_prompt = _tokens(cfg, batch=1)[:, :20]
    with pytest.raises(ValueError, match="wrap"):
        llama.generate(model, params, long_prompt, 4, cache_len=16)


def test_windowed_default_cache_is_window_sized(monkeypatch):
    """mistral-style long-context decode must NOT allocate max_len slots:
    the default cache is sized by the window, not the total context."""
    cfg = _f32(max_len=512, sliding_window=8)
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=1, seed=1)[:, :6]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    sizes = []
    real = llama.init_cache

    def spy(cfg_, batch, cache_len=None, dtype=None, **kw):
        sizes.append(cache_len)
        return real(cfg_, batch, cache_len, dtype, **kw)

    monkeypatch.setattr(llama, "init_cache", spy)
    # total 6+130=136 buckets to 256; window sizing caps at
    # max(bucket(8), bucket(6)) = 128 — the ring, not the context
    got = llama.generate(model, params, prompt, 130)
    assert sizes == [128], sizes
    assert got.shape == (1, 130)
    assert bool((got >= 0).all())
    # decode-vs-oracle parity incl. ring wrap is covered by
    # test_rolling_cache_windowed_decode_matches_oracle


def test_moe_every_zero_rejected():
    with pytest.raises(ValueError, match="moe_every"):
        llama.tiny(n_experts=4, moe_every=0)


def test_generate_accepts_array_temperature():
    """jnp/np scalar temperatures must neither crash the lru key nor
    fragment the compile cache vs the equal python float."""
    import numpy as np

    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=1)[:, :4]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    out = llama.generate(model, params, prompt, 2,
                         rng=jax.random.PRNGKey(1),
                         temperature=jnp.float32(0.8))
    assert out.shape == (1, 2)
    # the same array temperature maps to one cache entry (float32(0.8)
    # is a different float from the 0.8 literal, so THOSE can't unify)
    assert (llama._decode_fns(model, np.float32(0.8))
            is llama._decode_fns(model, jnp.float32(0.8)))


def test_moe_decode_gathers_single_expert():
    """The decode path must read ONE expert per token (sparse inference),
    and its output must equal the dense training-path dispatch."""
    from tf_operator_tpu.models.transformer import apply_with_aux

    cfg = _f32(n_experts=4, moe_every=1)
    model = llama.Llama(cfg)
    toks = _tokens(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    full = model.apply({"params": params}, toks)
    cache = llama.init_cache(cfg, 2)
    dec, _ = model.apply({"params": params}, toks, cache=cache, cache_pos=0)
    # gathered per-token expert == dense masked dispatch, to fp tolerance
    assert jnp.allclose(dec, full, atol=1e-4), float(jnp.abs(dec - full).max())


def test_moe_single_token_gather_matches_full_forward():
    """The L==1 gathered-expert decode branch must be NUMERICALLY right:
    prefill a prompt, take one cached single-token step, and compare its
    logits against the full forward over prompt+token (which routes all
    tokens through the dense dispatch)."""
    cfg = _f32(n_experts=4, moe_every=1)
    model = llama.Llama(cfg)
    toks = _tokens(cfg)[:, :9]
    prompt, last = toks[:, :8], toks[:, 8:9]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    cache = llama.init_cache(cfg, 2)
    _, cache = model.apply(
        {"params": params}, prompt, cache=cache, cache_pos=0)
    step_logits, _ = model.apply(
        {"params": params}, last, cache=cache, cache_pos=8)
    full = model.apply({"params": params}, toks)
    assert jnp.allclose(step_logits[:, 0], full[:, 8], atol=1e-4), float(
        jnp.abs(step_logits[:, 0] - full[:, 8]).max()
    )


# ------------------------------------------------------------- sampling
def test_top_k_one_equals_greedy():
    logits = jax.random.normal(jax.random.PRNGKey(0), (3, 50))
    greedy = jnp.argmax(logits, axis=-1)
    for seed in range(5):
        got = llama._select_token(logits, 1.0, jax.random.PRNGKey(seed),
                                  top_k=1)
        assert jnp.array_equal(got, greedy)


def test_top_k_samples_stay_in_top_k():
    logits = jax.random.normal(jax.random.PRNGKey(1), (2, 50))
    allowed = set(jnp.argsort(logits, axis=-1)[0, -5:].tolist())
    for seed in range(30):
        got = llama._select_token(logits, 1.0, jax.random.PRNGKey(seed),
                                  top_k=5)
        assert int(got[0]) in allowed


def test_top_p_tiny_equals_greedy():
    logits = jax.random.normal(jax.random.PRNGKey(2), (2, 50)) * 3
    greedy = jnp.argmax(logits, axis=-1)
    for seed in range(5):
        got = llama._select_token(logits, 1.0, jax.random.PRNGKey(seed),
                                  top_p=1e-6)
        assert jnp.array_equal(got, greedy)


def test_top_p_samples_stay_in_nucleus():
    logits = jnp.log(jnp.asarray(
        [[0.5, 0.3, 0.1, 0.05, 0.05]]))  # nucleus(0.75) = {0, 1}
    for seed in range(40):
        got = llama._select_token(logits, 1.0, jax.random.PRNGKey(seed),
                                  top_p=0.75)
        assert int(got[0]) in (0, 1)


def test_generate_with_sampling_knobs():
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :4]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    out = llama.generate(model, params, prompt, 4,
                         rng=jax.random.PRNGKey(3), temperature=0.9,
                         top_k=10, top_p=0.9)
    assert out.shape == (2, 4)
    assert bool((out >= 0).all()) and bool((out < cfg.vocab_size).all())


def test_sampling_knobs_bind_every_decode_step():
    """top_k=1 sampling == greedy for EVERY generated token (a regression
    here means the scan body dropped the knobs and only token 1 was
    truncated)."""
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :6]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    greedy = llama.generate(model, params, prompt, 8)
    sampled = llama.generate(model, params, prompt, 8,
                             rng=jax.random.PRNGKey(11), temperature=1.5,
                             top_k=1)
    assert jnp.array_equal(sampled, greedy)
    with pytest.raises(ValueError, match="top_k"):
        llama.generate(model, params, prompt, 2, rng=jax.random.PRNGKey(0),
                       temperature=1.0, top_k=cfg.vocab_size + 1)
    with pytest.raises(ValueError, match="top_p"):
        llama.generate(model, params, prompt, 2, rng=jax.random.PRNGKey(0),
                       temperature=1.0, top_p=1.5)


def test_eos_masks_rest_of_generation():
    """Once a sequence emits eos_id, every later slot is eos_id; a
    sequence that never emits it decodes normally (same-batch mix)."""
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=2)[:, :6]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    plain = llama.generate(model, params, prompt, 10)
    # pick row 0's 3rd greedy token as the "eos": rows diverge after it
    eos = int(plain[0, 2])
    out = llama.generate(model, params, prompt, 10, eos_id=eos)
    got = np.asarray(out) if hasattr(out, "shape") else out
    for b in range(2):
        row = list(map(int, got[b]))
        if eos in row:
            i = row.index(eos)
            assert all(t == eos for t in row[i:]), row
            # tokens BEFORE the first eos match the unmasked decode
            assert row[:i] == list(map(int, plain[b][:i]))
        else:
            assert row == list(map(int, plain[b]))
    # row 0 must actually have stopped at position 2
    assert int(got[0, 2]) == eos and int(got[0, 9]) == eos
    with pytest.raises(ValueError, match="eos_id"):
        llama.generate(model, params, prompt, 2, eos_id=cfg.vocab_size)


def test_negative_eos_rejected_before_allocation():
    cfg = _f32()
    model = llama.Llama(cfg)
    prompt = _tokens(cfg, batch=1)[:, :4]
    params = model.init(jax.random.PRNGKey(0), prompt, train=False)["params"]
    with pytest.raises(ValueError, match="eos_id"):
        llama.generate(model, params, prompt, 2, eos_id=-2)


def test_mistral_swa_under_ring_matches_einsum_model():
    """The flagship long-context combination (VERDICT r3 weak #5): a
    mistral-style windowed config running its training forward through
    RING attention over a sequence-parallel mesh must match the
    single-device einsum model exactly."""
    from tf_operator_tpu.ops.ring_attention import make_ring_attention_fn
    from tf_operator_tpu.parallel.mesh import make_mesh

    mesh = make_mesh({"tp": 4, "dp": 2})
    cfg = _f32(sliding_window=10)
    toks = _tokens(cfg)
    model = llama.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    want = model.apply({"params": params}, toks)
    ring_cfg = _f32(
        sliding_window=10,
        attention_fn=make_ring_attention_fn(mesh, axis_name="tp"))
    with mesh:
        got = jax.jit(
            lambda p, t: llama.Llama(ring_cfg).apply({"params": p}, t)
        )(params, toks)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-3, rtol=2e-3)


def test_mistral_swa_under_ring_flash_zigzag_grads():
    """SWA + zigzag pallas ring + GQA end to end through a llama loss:
    grads wrt params match the einsum model (the storage permutation is
    applied to tokens AND positions outside the step; labels shift in
    logical order first)."""
    from tf_operator_tpu.ops import zigzag as zz
    from tf_operator_tpu.ops.ring_flash import make_ring_flash_attention_fn
    from tf_operator_tpu.parallel.mesh import make_mesh

    n = 4
    mesh = make_mesh({"tp": n, "dp": 2})
    cfg = _f32(sliding_window=10, max_len=64)
    toks = _tokens(cfg)
    model = llama.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]

    def loss_ref(p):
        return (model.apply({"params": p}, toks).astype(jnp.float32) ** 2
                ).mean()

    ring_cfg = _f32(
        sliding_window=10, max_len=64,
        attention_fn=make_ring_flash_attention_fn(
            mesh, axis_name="tp", interpret=True, layout="zigzag"))
    perm = zz.storage_perm(n, cfg.max_len)
    toks_z = toks[:, perm]
    positions = jnp.asarray(perm, jnp.int32)[None, :].repeat(2, axis=0)

    def loss_ring(p):
        out = llama.Llama(ring_cfg).apply(
            {"params": p}, toks_z, positions=positions)
        # un-permute before the loss so the two losses see identical rows
        inv = jnp.asarray(zz.inverse_perm(perm))
        return (out[:, inv].astype(jnp.float32) ** 2).mean()

    g_ref = jax.grad(loss_ref)(params)
    with mesh:
        g_ring = jax.jit(jax.grad(loss_ring))(params)
    flat_ref = jax.tree_util.tree_leaves_with_path(g_ref)
    flat_ring = dict(jax.tree_util.tree_leaves_with_path(g_ring))
    for path, want in flat_ref:
        got = flat_ring[path]
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=5e-3, rtol=5e-3,
            err_msg=jax.tree_util.keystr(path))


# ------------------------------------------------------------ chunked prefill
def test_chunked_prefill_matches_single_pass():
    """Chunked prefill (ragged last chunk included) must produce the
    exact tokens of the one-pass prefill."""
    cfg = _f32(max_len=128)
    toks = _tokens(cfg, batch=2)[:, :40]
    model = llama.Llama(cfg)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    want = llama.generate(model, params, toks, max_new_tokens=12)
    got = llama.generate(model, params, toks, max_new_tokens=12,
                         prefill_chunk=16)  # 16,16,8 segments
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_chunked_prefill_streams_long_prompt_through_window_ring():
    """The headline case: a sliding-window model whose PROMPT exceeds the
    ring cache. Chunked prefill streams it through O(window) slots; the
    result must equal the same model prefilled with a big cache (the
    window hides everything older either way)."""
    cfg = _f32(sliding_window=16, max_len=256)
    model = llama.Llama(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 100), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt,
                        train=False)["params"]
    want = llama.generate(model, params, prompt, max_new_tokens=10,
                          cache_len=128)  # prompt fits: one-pass oracle
    got = llama.generate(model, params, prompt, max_new_tokens=10,
                         cache_len=32, prefill_chunk=16)  # prompt 100 > 32
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_chunked_prefill_validation():
    cfg = _f32(max_len=128)
    model = llama.Llama(cfg)
    toks = jnp.zeros((1, 40), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    # a chunk >= the prompt is a single-segment prefill — identical to
    # the unchunked path, even when the chunk exceeds max_len (the
    # streaming-only sizing rules must not reject or mis-size it)
    want = llama.generate(model, params, toks, 4)
    got = llama.generate(model, params, toks, 4, prefill_chunk=600)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    with pytest.raises(ValueError, match="divide"):
        llama.generate(model, params, toks, 4, cache_len=128,
                       prefill_chunk=24)  # streams (24 < 40), 128 % 24 != 0
    # a full-causal model cannot stream past its cache — chunking bounds
    # activations, not visibility
    with pytest.raises(ValueError, match="exceeds cache"):
        llama.generate(model, params, toks, 4, cache_len=32,
                       prefill_chunk=16)
    # a WINDOWED model's over-long prompt without chunking refuses with
    # the prefill_chunk hint (full-causal ones hit the total>cache check
    # first, where streaming could not help anyway)
    wcfg = _f32(sliding_window=16, max_len=128)
    wmodel = llama.Llama(wcfg)
    wparams = wmodel.init(jax.random.PRNGKey(0), toks,
                          train=False)["params"]
    with pytest.raises(ValueError, match="prefill_chunk"):
        llama.generate(wmodel, wparams, toks, 4, cache_len=32)


def test_auto_cache_len_chunked_prefill_gives_window_ring():
    """With prefill_chunk set, a sliding-window model's DEFAULT cache is
    O(window + chunk), not O(prompt) — the documented '128k prompt
    through an O(window) ring' must materialize without the caller
    passing cache_len (the inference CLI never does)."""
    cfg = _f32(sliding_window=512, max_len=16384)
    # no chunk: the one-pass prefill write must fit, cache grows with it
    assert llama.auto_cache_len(cfg, 4096, 4160) == 4096
    # chunked: window + one chunk's eviction band, chunk-aligned
    c = llama.auto_cache_len(cfg, 4096, 4160, prefill_chunk=128)
    assert c == 640
    assert c % 128 == 0 and c - cfg.sliding_window >= 128
    # a non-128-multiple chunk still divides the result (generate()
    # requires chunk | cache) and keeps the eviction band
    c = llama.auto_cache_len(cfg, 4096, 4160, prefill_chunk=96)
    assert c % 96 == 0 and c >= cfg.sliding_window + 96
    # full causal: chunking bounds activations, not visibility — the
    # cache still holds the whole sequence, rounded to a chunk multiple
    fc = _f32(max_len=16384)
    c = llama.auto_cache_len(fc, 4096, 4160, prefill_chunk=96)
    assert c >= 4160 and c % 96 == 0
    # short prompt: the chunked default never exceeds the unchunked one
    assert llama.auto_cache_len(cfg, 64, 128, prefill_chunk=64) == 128
    # the chunk round-up must never cross the RoPE-table bound: with a
    # chunk that does not divide max_len and total in the top bucket,
    # the default falls back to the largest chunk multiple that fits
    # (init_cache would refuse anything past max_len)
    edge = _f32(max_len=512)
    c = llama.auto_cache_len(edge, 500, 510, prefill_chunk=96)
    assert c == 480 and c <= edge.max_len and c % 96 == 0
    # ...and generate() then refuses the genuinely infeasible request
    # with its own accurate message, not init_cache's
    model = llama.Llama(edge)
    toks = jnp.zeros((1, 500), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:, :8],
                        train=False)["params"]
    with pytest.raises(ValueError, match="exceeds cache"):
        llama.generate(model, params, toks, 10, prefill_chunk=96)


def test_generate_default_cache_streams_long_prompt():
    """End to end through the DEFAULT sizing: windowed model, prompt
    larger than the auto ring, no cache_len argument — generate() must
    stream exactly (vs a big-cache oracle) rather than allocate
    O(prompt)."""
    cfg = _f32(sliding_window=16, max_len=512)
    model = llama.Llama(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 300), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt,
                        train=False)["params"]
    assert llama.auto_cache_len(cfg, 300, 310, prefill_chunk=16) == 128
    want = llama.generate(model, params, prompt, max_new_tokens=10,
                          cache_len=384)
    got = llama.generate(model, params, prompt, max_new_tokens=10,
                         prefill_chunk=16)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_chunked_prefill_rejects_window_evicting_chunks():
    """A segment write must not evict positions its own queries still
    attend: window=24, cache=32, chunk=32 divides the cache but evicts
    the whole ring before attention runs — reject, never approximate."""
    cfg = _f32(sliding_window=24, max_len=256)
    model = llama.Llama(cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 64), 0,
                                cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(0), prompt,
                        train=False)["params"]
    with pytest.raises(ValueError, match="evict"):
        llama.generate(model, params, prompt, 4, cache_len=32,
                       prefill_chunk=32)
    # at the safe bound (chunk <= cache - window) streaming stays exact
    want = llama.generate(model, params, prompt, 4, cache_len=128)
    got = llama.generate(model, params, prompt, 4, cache_len=32,
                         prefill_chunk=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
