"""Native (C++) runtime core vs the Python fallback: one contract suite runs
against both implementations, so the ctypes layer can never drift from the
reference workqueue/expectations semantics (client-go contract, SURVEY §5.2).
"""
import threading
import time

import pytest

from tf_operator_tpu import native
from tf_operator_tpu.engine.expectations import ControllerExpectations
from tf_operator_tpu.k8s.informer import RateLimitingQueue

# Python-param tests always run; only native params/tests skip without the .so
needs_native = pytest.mark.skipif(
    not native.native_available(), reason="libtpuoperator.so not built"
)


def _queues():
    return [
        pytest.param(lambda: RateLimitingQueue(), id="python"),
        pytest.param(
            lambda: native.NativeRateLimitingQueue(), id="native", marks=needs_native
        ),
    ]


def _expectations():
    return [
        pytest.param(lambda: ControllerExpectations(), id="python"),
        pytest.param(
            lambda: native.NativeControllerExpectations(),
            id="native",
            marks=needs_native,
        ),
    ]


@pytest.mark.parametrize("mk", _queues())
class TestQueueContract:
    def test_fifo_and_dedup(self, mk):
        q = mk()
        q.add("a")
        q.add("b")
        q.add("a")  # dedup while queued
        assert q.get(timeout=1) == "a"
        assert q.get(timeout=1) == "b"
        assert q.get(timeout=0.02) is None

    def test_dirty_requeue_on_done(self, mk):
        q = mk()
        q.add("a")
        assert q.get(timeout=1) == "a"
        q.add("a")  # while processing: marks dirty, not queued
        assert len(q) == 0
        q.done("a")
        assert q.get(timeout=1) == "a"

    def test_add_after_fires(self, mk):
        q = mk()
        q.add_after("later", 0.05)
        assert q.pending_delayed() == 1
        t0 = time.monotonic()
        assert q.get(timeout=2) == "later"
        assert time.monotonic() - t0 >= 0.04

    def test_add_after_zero_is_immediate(self, mk):
        q = mk()
        q.add_after("now", 0)
        assert q.get(timeout=1) == "now"

    def test_rate_limiter_backoff_and_forget(self, mk):
        q = mk()
        for _ in range(3):
            q.add_rate_limited("k")
        assert q.num_requeues("k") == 3
        q.forget("k")
        assert q.num_requeues("k") == 0

    def test_shutdown_unblocks_getters(self, mk):
        q = mk()
        got = []

        def getter():
            got.append(q.get(timeout=5))

        t = threading.Thread(target=getter)
        t.start()
        time.sleep(0.05)
        q.shut_down()
        t.join(timeout=2)
        assert not t.is_alive()
        assert got == [None]

    def test_concurrent_producers_consumers(self, mk):
        q = mk()
        n, consumed, lock = 200, [], threading.Lock()

        def consumer():
            while True:
                item = q.get(timeout=1)
                if item is None:
                    return
                with lock:
                    consumed.append(item)
                q.done(item)

        threads = [threading.Thread(target=consumer) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(n):
            q.add(f"k{i}")
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with lock:
                if len(set(consumed)) == n:
                    break
            time.sleep(0.01)
        q.shut_down()
        for t in threads:
            t.join(timeout=2)
        assert len(set(consumed)) == n


@pytest.mark.parametrize("mk", _expectations())
class TestExpectationsContract:
    def test_unset_key_is_satisfied(self, mk):
        assert mk().satisfied_expectations("ns/j/worker/pods")

    def test_creations_block_until_observed(self, mk):
        e = mk()
        e.expect_creations("k", 2)
        assert not e.satisfied_expectations("k")
        e.creation_observed("k")
        assert not e.satisfied_expectations("k")
        e.creation_observed("k")
        assert e.satisfied_expectations("k")

    def test_deletions_block_until_observed(self, mk):
        e = mk()
        e.expect_deletions("k", 1)
        assert not e.satisfied_expectations("k")
        e.deletion_observed("k")
        assert e.satisfied_expectations("k")

    def test_raise_and_lower(self, mk):
        e = mk()
        e.raise_expectations("k", 1, 1)
        assert not e.satisfied_expectations("k")
        e.lower_expectations("k", 1, 1)
        assert e.satisfied_expectations("k")

    def test_delete_clears(self, mk):
        e = mk()
        e.expect_creations("k", 5)
        e.delete_expectations("k")
        assert e.satisfied_expectations("k")

    def test_overshoot_stays_satisfied(self, mk):
        e = mk()
        e.expect_creations("k", 1)
        e.creation_observed("k")
        e.creation_observed("k")  # extra observation must not wrap
        assert e.satisfied_expectations("k")


@needs_native
def test_native_expectation_ttl_expires():
    e = native.NativeControllerExpectations(ttl_seconds=0.05)
    e.expect_creations("k", 3)
    assert not e.satisfied_expectations("k")
    time.sleep(0.08)
    assert e.satisfied_expectations("k")


def test_factories_pick_fallback_when_disabled(monkeypatch):
    if native.native_available():
        assert isinstance(native.make_queue(), native.NativeRateLimitingQueue)
        assert isinstance(
            native.make_expectations(), native.NativeControllerExpectations
        )
    monkeypatch.setenv("TPU_OPERATOR_NATIVE", "0")
    # env flag is read at library-load time; force a fresh decision
    native._lib_loaded = False
    native._lib = None
    try:
        assert isinstance(native.make_queue(), RateLimitingQueue)
        assert isinstance(native.make_expectations(), ControllerExpectations)
    finally:
        native._lib_loaded = False
        native._lib = None


def test_fallback_queue_honors_tuning(monkeypatch):
    monkeypatch.setenv("TPU_OPERATOR_NATIVE", "0")
    native._lib_loaded = False
    native._lib = None
    try:
        q = native.make_queue(base_delay=0.5, max_delay=30.0)
        assert isinstance(q, RateLimitingQueue)
        assert q._rate_limiter.base_delay == 0.5
        assert q._rate_limiter.max_delay == 30.0
    finally:
        native._lib_loaded = False
        native._lib = None


@needs_native
def test_native_queue_oversized_key_raises():
    q = native.NativeRateLimitingQueue()
    q.add("x" * 5000)
    with pytest.raises(ValueError, match="exceeds"):
        q.get(timeout=1)


@needs_native
def test_native_queue_oversized_key_dropped_not_wedged():
    """The bad key must be popped and dropped — left at the head it would
    re-raise on every subsequent get, permanently wedging the worker pool
    (ADVICE r1)."""
    q = native.NativeRateLimitingQueue()
    q.add("x" * 5000)
    q.add("good-key")
    with pytest.raises(ValueError):
        q.get(timeout=1)
    assert q.get(timeout=1) == "good-key"
    q.done("good-key")
    assert len(q) == 0


@needs_native
def test_native_queue_close_with_blocked_getter_is_safe():
    """A getter still blocked in the native call when the queue is finalized
    must not touch freed memory: close() shuts down (waking it) and the last
    in-flight call frees the handle (ADVICE r1, medium)."""
    import threading

    q = native.NativeRateLimitingQueue()
    results = []

    def getter():
        results.append(q.get(timeout=30))

    t = threading.Thread(target=getter)
    t.start()
    time.sleep(0.2)  # getter is blocked inside wq_get
    q._hd.close()  # what __del__ does, while the call is in flight
    t.join(timeout=5)
    assert not t.is_alive(), "blocked getter must be woken by close()"
    assert results == [None]
    assert q._hd.h is None, "handle freed exactly once, by the last exiter"
    # post-close calls are refused, not crashes
    q.add("late")
    assert q.get(timeout=0.01) is None
    assert len(q) == 0


@needs_native
def test_native_expectations_close_refuses_late_calls():
    e = native.NativeControllerExpectations()
    e.expect_creations("k", 2)
    assert not e.satisfied_expectations("k")
    e._hd.close()
    # closed: benign defaults, no UAF
    e.creation_observed("k")
    assert e.satisfied_expectations("k") is True


@needs_native
def test_native_queue_shutting_down_property():
    q = native.NativeRateLimitingQueue()
    assert not q.shutting_down
    q.shut_down()
    assert q.shutting_down


@needs_native
def test_native_queue_throughput_smoke():
    """The native queue must sustain an operator-scale add/get/done cycle
    quickly (sanity perf gate, not a benchmark)."""
    q = native.NativeRateLimitingQueue()
    t0 = time.monotonic()
    for round_ in range(20):
        for i in range(100):
            q.add(f"ns/job-{i}")
        for _ in range(100):
            item = q.get(timeout=1)
            q.done(item)
            q.forget(item)
    dt = time.monotonic() - t0
    assert dt < 2.0, f"native queue too slow: {dt:.3f}s for 2k cycles"
