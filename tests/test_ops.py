"""Kernel tests (pallas interpret mode on CPU; same code compiles on TPU).

Mirrors the reference's tier-1 strategy (SURVEY.md §4.1: table-driven unit
tests of pure logic) applied to the compute path the reference doesn't have.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models.transformer import dot_product_attention
from tf_operator_tpu.ops.flash_attention import flash_attention


def _rand_qkv(key, b, s, h, d, dtype):
    kq, kk, kv = jax.random.split(key, 3)
    shape = (b, s, h, d)
    return (jax.random.normal(kq, shape, dtype),
            jax.random.normal(kk, shape, dtype),
            jax.random.normal(kv, shape, dtype))


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5), (jnp.bfloat16, 2e-2)])
def test_flash_forward_matches_reference(causal, dtype, tol):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), 2, 128, 2, 32, dtype)
    got = flash_attention(q, k, v, causal, blk_q=64, blk_k=64, interpret=True)
    want = dot_product_attention(q, k, v, causal)
    np.testing.assert_allclose(
        got.astype(jnp.float32), want.astype(jnp.float32), atol=tol, rtol=tol)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_grads_match_reference(causal):
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), 1, 128, 2, 16, jnp.float32)
    cot = jax.random.normal(jax.random.PRNGKey(2), q.shape, jnp.float32)

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v, causal) * cot)

    flash = functools.partial(flash_attention, blk_q=32, blk_k=64,
                              interpret=True)
    g_got = jax.grad(functools.partial(loss, flash), argnums=(0, 1, 2))(q, k, v)
    g_want = jax.grad(functools.partial(loss, dot_product_attention),
                      argnums=(0, 1, 2))(q, k, v)
    for got, want, name in zip(g_got, g_want, "qkv"):
        np.testing.assert_allclose(got, want, atol=2e-4, rtol=2e-4,
                                   err_msg=f"d{name}")


def test_flash_uneven_seq_falls_back():
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), 1, 100, 2, 16, jnp.float32)
    got = flash_attention(q, k, v, True, interpret=True)
    want = dot_product_attention(q, k, v, True)
    np.testing.assert_allclose(got, want, atol=1e-5, rtol=1e-5)


def test_flash_inside_transformer():
    """attention_fn plug point: tiny model forward agrees with einsum path."""
    from tf_operator_tpu.models import transformer as tfm

    cfg_ref = tfm.tiny(causal=True)
    cfg_flash = tfm.tiny(
        causal=True,
        attention_fn=functools.partial(flash_attention, interpret=True))
    tokens = jax.random.randint(jax.random.PRNGKey(4), (2, 64), 0, 255)
    params = tfm.Transformer(cfg_ref).init(jax.random.PRNGKey(5), tokens)
    out_ref = tfm.Transformer(cfg_ref).apply(params, tokens)
    out_flash = tfm.Transformer(cfg_flash).apply(params, tokens)
    # tiny cfg runs bf16: the flash kernel scores in f32 while the einsum
    # path scores in bf16, so agreement is bounded by bf16 resolution.
    np.testing.assert_allclose(out_ref, out_flash, atol=1e-1, rtol=5e-2)


def test_snap_block_keeps_kernel_engaged():
    """Preferred blocks that don't divide S snap down to a 128-multiple
    divisor instead of bailing to the einsum fallback."""
    from tf_operator_tpu.ops.flash_attention import _snap_block

    assert _snap_block(1024, 2048) == 1024
    assert _snap_block(1024, 1536) == 768   # largest 128-mult divisor
    assert _snap_block(512, 2560) == 512
    assert _snap_block(1024, 2560) == 640
    assert _snap_block(512, 64) == 64       # s <= blk: whole-dim block
    assert _snap_block(512, 200) == 200     # ditto (full dim is Mosaic-legal)
    assert _snap_block(512, 600) is None    # no aligned divisor -> fallback
