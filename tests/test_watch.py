"""SDK watch helper: streamed status transitions (reference tf_job_watch.py
surface, SURVEY §2.6) against the live operator on the fake cluster."""
import threading

import pytest

from tf_operator_tpu.sdk.client import TFJobClient
from tf_operator_tpu.sdk.watch import job_state, watch_job


def _job_dict(name="w1"):
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "tfReplicaSpecs": {
                "Worker": {
                    "replicas": 1,
                    "template": {
                        "spec": {
                            "containers": [
                                {"name": "tensorflow", "image": "x"}
                            ]
                        }
                    },
                }
            }
        },
    }


def test_job_state_reads_latest_true_condition():
    job = {"status": {"conditions": [
        {"type": "Created", "status": "True"},
        {"type": "Running", "status": "False"},
        {"type": "Succeeded", "status": "True"},
    ]}}
    assert job_state(job) == "Succeeded"
    assert job_state({}) == ""


def test_watch_yields_current_then_transitions():
    from tf_operator_tpu.k8s.fake import FakeCluster

    cluster = FakeCluster()
    client = TFJobClient(cluster)
    client.create(_job_dict())

    seen = []

    def consume():
        for ev, job in watch_job(cluster, "TFJob", "w1", timeout=5):
            seen.append((ev, job_state(job)))

    t = threading.Thread(target=consume)
    t.start()
    # drive status transitions like the controller would (fresh read each
    # time: updates bump resourceVersion)
    for cond in ("Created", "Running", "Succeeded"):
        j = cluster.get("TFJob", "default", "w1")
        j.setdefault("status", {}).setdefault("conditions", []).append(
            {"type": cond, "status": "True"}
        )
        cluster.update("TFJob", j)
    t.join(timeout=5)
    assert not t.is_alive()
    assert seen[0] == ("ADDED", "")
    assert seen[-1][1] == "Succeeded"  # stopped at terminal


def test_watch_stops_on_delete():
    from tf_operator_tpu.k8s.fake import FakeCluster

    cluster = FakeCluster()
    client = TFJobClient(cluster)
    client.create(_job_dict("gone"))
    events = []

    def consume():
        for ev, _ in client.watch("gone", timeout=5):
            events.append(ev)

    t = threading.Thread(target=consume)
    t.start()
    client.delete("gone")
    t.join(timeout=5)
    assert not t.is_alive()
    assert events == ["ADDED", "DELETED"]


def test_watch_timeout_raises():
    from tf_operator_tpu.k8s.fake import FakeCluster

    cluster = FakeCluster()
    TFJobClient(cluster).create(_job_dict("idle"))
    with pytest.raises(TimeoutError):
        for _ in watch_job(cluster, "TFJob", "idle", timeout=0.1):
            pass


def test_watch_unsubscribes_handler():
    from tf_operator_tpu.k8s.fake import FakeCluster

    cluster = FakeCluster()
    client = TFJobClient(cluster)
    client.create(_job_dict("u1"))
    before = sum(len(v) for v in cluster._handlers.values())
    try:
        for _ in watch_job(cluster, "TFJob", "u1", timeout=0.05):
            pass
    except TimeoutError:
        pass
    after = sum(len(v) for v in cluster._handlers.values())
    assert after == before


def test_watch_end_to_end_with_operator():
    """Full loop: the live operator + fake kubelet drive the job while a
    concurrent watch streams its states through to Succeeded."""
    from tf_operator_tpu.cmd.manager import OperatorManager
    from tf_operator_tpu.cmd.options import ServerOptions
    from tf_operator_tpu.controllers.registry import EnabledSchemes
    from tf_operator_tpu.e2e.kubelet import FakeKubelet
    from tf_operator_tpu.k8s.fake import FakeCluster

    cluster = FakeCluster()
    mgr = OperatorManager(
        cluster,
        ServerOptions(enabled_schemes=EnabledSchemes(["TFJob"]), threadiness=2),
    )
    mgr.start()
    kubelet = FakeKubelet(cluster)
    client = TFJobClient(cluster)
    try:
        states = []
        done = threading.Event()

        def consume():
            for _, job in client.watch("full", timeout=10):
                states.append(job_state(job))
            done.set()

        t = threading.Thread(target=consume)
        t.start()
        client.create(_job_dict("full"))
        client.wait_for_condition("full", ["Running"])
        kubelet.wait_running("default", "full-worker-0", 10)
        kubelet.terminate_replica("default", "full-worker-0", 0)
        assert done.wait(timeout=10)
        t.join(timeout=2)
        assert states[-1] == "Succeeded"
        assert "Running" in states
    finally:
        kubelet.stop_all()
        mgr.stop()
