"""Continuous batching (models/serving.serve_loop): slot admission —
rows join and leave mid-stream — with per-request outputs EXACTLY equal
to isolated llama.generate calls (greedy).  Batching changes throughput,
never tokens."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models import llama
from tf_operator_tpu.models.serving import serve_loop


def _f32(**kw):
    kw.setdefault("dtype", jnp.float32)
    return llama.tiny(**kw)


def _setup(seed=0, **cfg_kw):
    cfg = _f32(**cfg_kw)
    model = llama.Llama(cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    params = model.init(jax.random.PRNGKey(seed), toks,
                        train=False)["params"]
    return cfg, model, params


def _prompts(cfg, lengths, seed=1):
    key = jax.random.PRNGKey(seed)
    out = []
    for i, n in enumerate(lengths):
        key, k = jax.random.split(key)
        out.append(jax.random.randint(k, (n,), 0, cfg.vocab_size))
    return out


def _oracle(model, params, prompt, max_new, eos_id=None):
    """Isolated generation, truncated AFTER the first EOS (serve_loop's
    per-request stopping contract)."""
    row = llama.generate(model, params, prompt[None, :], max_new,
                         eos_id=eos_id)
    toks = [int(t) for t in np.asarray(row[0])]
    if eos_id is not None and eos_id in toks:
        toks = toks[: toks.index(eos_id) + 1]
    return toks


def test_outputs_equal_isolated_generation():
    """More requests than slots, ragged prompt lengths: every request's
    tokens must equal its own isolated generate run — admission order
    and lane sharing must not leak between rows."""
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6, 11, 3, 9, 7, 5])
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=12)
    assert len(res) == len(prompts)
    for r, p in zip(res, prompts):
        assert r.tokens == _oracle(model, params, p, 12), (
            f"slot {r.slot} diverged")


def test_slots_churn_midstream():
    """Different budgets per... the budget is global, so churn comes
    from EOS: pick each request's own greedy EOS token so finishes are
    staggered, then check lanes were actually reused and late requests
    were admitted after step 0."""
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6, 8, 5, 7, 9, 4, 6, 8], seed=3)
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=10)
    slots_used = {r.slot for r in res}
    assert slots_used == {0, 1}
    late = [r for r in res if r.admitted_at_step > 0]
    assert len(late) >= 4  # 8 requests through 2 lanes => >= 6 waited
    # lanes were reused: some request finished before another started
    finishes = sorted(r.finished_at_step for r in res)
    starts = sorted(r.admitted_at_step for r in res)[len(slots_used):]
    assert starts and starts[0] >= finishes[0]


def test_eos_frees_slot_early():
    """A request whose greedy stream hits EOS frees its lane: with
    eos_id chosen as the second greedy token of request 0, request 0
    finishes in 2 tokens and the queued request reuses its slot."""
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6, 9, 7], seed=5)
    free = _oracle(model, params, prompts[0], 8)
    eos = free[1]  # greedy token 2 of request 0
    res = serve_loop(model, params, prompts, slots=1, max_new_tokens=8,
                     eos_id=eos)
    for r, p in zip(res, prompts):
        assert r.tokens == _oracle(model, params, p, 8, eos_id=eos)
    assert len(res[0].tokens) == 2 and res[0].tokens[-1] == eos


def test_windowed_ring_and_chunked_prefill():
    """Sliding-window model: per-slot O(window) rings, long prompts
    streaming in via chunked prefill — still exact per request."""
    cfg, model, params = _setup(max_len=512, sliding_window=8)
    prompts = _prompts(cfg, [40, 22, 33], seed=7)
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=10,
                     cache_len=16, prefill_chunk=4)
    for r, p in zip(res, prompts):
        want = [int(t) for t in np.asarray(llama.generate(
            model, params, p[None, :], 10, cache_len=16,
            prefill_chunk=4)[0])]
        assert r.tokens == want


def test_int8_weights_and_kv_compose():
    """Both int8 streams under the serve loop: tokens equal isolated
    int8 generation."""
    from tf_operator_tpu.models import quant

    cfg, model, params = _setup(max_len=128)
    qp = quant.quantize_params(params)
    dq = quant.make_dequantizer(cfg.dtype)
    prompts = _prompts(cfg, [6, 9, 4], seed=9)
    res = serve_loop(model, qp, prompts, slots=2, max_new_tokens=8,
                     params_transform=dq, kv_quant=True)
    for r, p in zip(res, prompts):
        want = [int(t) for t in np.asarray(llama.generate(
            model, qp, p[None, :], 8, params_transform=dq,
            kv_quant=True)[0])]
        assert r.tokens == want


def test_sampling_runs_and_is_seed_deterministic():
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6, 8], seed=11)
    kw = dict(slots=2, max_new_tokens=8, temperature=0.8, top_k=20)
    a = serve_loop(model, params, prompts, rng=jax.random.PRNGKey(1),
                   **kw)
    b = serve_loop(model, params, prompts, rng=jax.random.PRNGKey(1),
                   **kw)
    c = serve_loop(model, params, prompts, rng=jax.random.PRNGKey(2),
                   **kw)
    assert [r.tokens for r in a] == [r.tokens for r in b]
    assert [r.tokens for r in a] != [r.tokens for r in c]
    for r in a:
        assert all(0 <= t < cfg.vocab_size for t in r.tokens)


def test_block_size_invariance():
    """The decode-block size (steps_per_sync) is a scheduling knob, not
    a semantics knob: per-request TOKENS must be identical for block
    sizes 1, 3, and 8 (greedy; only admission timing may differ)."""
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6, 9, 4, 7], seed=13)
    outs = []
    for n in (1, 3, 8):
        res = serve_loop(model, params, prompts, slots=2,
                         max_new_tokens=10, steps_per_sync=n)
        outs.append([r.tokens for r in res])
    assert outs[0] == outs[1] == outs[2]


def test_eos_mid_block_discards_overshoot():
    """EOS landing mid-block: the lane's block-edge overshoot tokens are
    discarded, output still ends exactly at the EOS."""
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6], seed=5)
    free = _oracle(model, params, prompts[0], 12)
    eos = free[4]  # 5th token; block size 8 -> 3 overshoot steps
    res = serve_loop(model, params, prompts, slots=1, max_new_tokens=12,
                     eos_id=eos, steps_per_sync=8)
    want = _oracle(model, params, prompts[0], 12, eos_id=eos)
    assert res[0].tokens == want
    assert res[0].tokens[-1] == eos


def test_validation():
    cfg, model, params = _setup(max_len=64)
    p = _prompts(cfg, [6])
    assert serve_loop(model, params, []) == []
    with pytest.raises(ValueError, match="slots"):
        serve_loop(model, params, p, slots=0)
    with pytest.raises(ValueError, match="max_new"):
        serve_loop(model, params, p, max_new_tokens=0)
    with pytest.raises(ValueError, match="exceeds max_len"):
        serve_loop(model, params, p, max_new_tokens=60)
    with pytest.raises(ValueError, match="needs an rng"):
        serve_loop(model, params, p, temperature=0.5)
    with pytest.raises(ValueError, match="eos_id"):
        serve_loop(model, params, p, eos_id=cfg.vocab_size,
                   max_new_tokens=4)
    with pytest.raises(ValueError, match="top_k"):
        serve_loop(model, params, p, top_k=-5, max_new_tokens=4)
    with pytest.raises(ValueError, match="top_p"):
        serve_loop(model, params, p, top_p=1.5, max_new_tokens=4)
    with pytest.raises(ValueError, match="steps_per_sync"):
        serve_loop(model, params, p, steps_per_sync=0, max_new_tokens=4)
    with pytest.raises(ValueError, match="empty"):
        serve_loop(model, params, [jnp.zeros((0,), jnp.int32)])
    with pytest.raises(ValueError, match="cannot stream"):
        serve_loop(model, params, _prompts(cfg, [40]), cache_len=16,
                   max_new_tokens=4)  # full causal: total > cache
    with pytest.raises(ValueError, match="cannot stream"):
        # the subtler case: the PROMPT fits the cache but decode would
        # wrap the ring mid-stream — must refuse, not silently corrupt
        serve_loop(model, params, _prompts(cfg, [10]), cache_len=16,
                   max_new_tokens=20)
    wcfg, wmodel, wparams = _setup(max_len=256, sliding_window=32)
    with pytest.raises(ValueError, match="visible positions"):
        serve_loop(wmodel, wparams, _prompts(wcfg, [10]), cache_len=16,
                   max_new_tokens=40)  # ring smaller than the window
    with pytest.raises(ValueError, match="prefill_chunk must be"):
        serve_loop(model, params, p, prefill_chunk=0, max_new_tokens=4)
    # a LATER request's infeasible prompt must fail before ANY request
    # decodes, not mid-serve after request 0 completed
    wcfg2, wmodel2, wparams2 = _setup(max_len=512, sliding_window=8)
    with pytest.raises(ValueError, match="request 1: prompt 40"):
        serve_loop(wmodel2, wparams2, _prompts(wcfg2, [10, 40]),
                   cache_len=16, max_new_tokens=4, slots=1)


# ------------------------------------------------- speculative serving
def _draft_setup(cfg, seed=9):
    import dataclasses

    d_cfg = dataclasses.replace(cfg, n_layers=1)
    d_model = llama.Llama(d_cfg)
    d_params = d_model.init(jax.random.PRNGKey(seed),
                            jnp.zeros((1, 8), jnp.int32),
                            train=False)["params"]
    return d_model, d_params


def test_spec_serve_greedy_exact_vs_isolated():
    """Speculative continuous batching: per-lane draft+verify rounds
    must leave every request's greedy tokens EXACTLY equal to isolated
    generate — speculation and lane sharing change throughput only."""
    cfg, model, params = _setup(max_len=256)
    d_model, d_params = _draft_setup(cfg)
    prompts = _prompts(cfg, [6, 11, 3, 9, 7, 5])
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=12,
                     draft=d_model, draft_params=d_params, spec_k=3,
                     steps_per_sync=2)
    for r, p in zip(res, prompts):
        assert r.tokens == _oracle(model, params, p, 12), (
            f"slot {r.slot} diverged under speculation")


def test_spec_serve_eos_frees_slot():
    """A lane that hits EOS mid-round finishes (overshoot discarded)
    and its slot admits the next request; outputs still oracle-exact."""
    cfg, model, params = _setup(max_len=256)
    d_model, d_params = _draft_setup(cfg)
    prompts = _prompts(cfg, [5, 8, 4, 6])
    # pick an eos that actually occurs early for at least one request
    base = [_oracle(model, params, p, 16) for p in prompts]
    flat = [t for toks in base for t in toks]
    eos = max(set(flat), key=flat.count)
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=16,
                     eos_id=eos, draft=d_model, draft_params=d_params,
                     spec_k=2, steps_per_sync=3)
    for r, p in zip(res, prompts):
        assert r.tokens == _oracle(model, params, p, 16, eos_id=eos)


def test_spec_serve_window_ring_and_int8():
    """The flagship composition: sliding-window rings on BOTH models,
    int8 weights + int8 KV caches, speculative rounds through shared
    lanes — greedy still oracle-exact (over the same int8-KV
    representation)."""
    from tf_operator_tpu.models import quant

    cfg, model, params = _setup(max_len=256, sliding_window=8,
                                n_layers=2)
    d_model, d_params = _draft_setup(cfg)
    prompts = _prompts(cfg, [7, 12, 5])
    q_params = quant.quantize_params(params)
    q_draft = quant.quantize_params(d_params)
    xform = quant.make_dequantizer(cfg.dtype)
    kw = dict(slots=2, max_new_tokens=10, cache_len=16,
              draft=d_model, spec_k=3, kv_quant=True,
              params_transform=xform, draft_transform=xform)
    res = serve_loop(model, q_params, prompts,
                     draft_params=q_draft, **kw)
    for r, p in zip(res, prompts):
        want = llama.generate(model, q_params, p[None, :], 10,
                              params_transform=xform, cache_len=16,
                              kv_quant=True)
        assert r.tokens == [int(t) for t in np.asarray(want[0])], (
            f"slot {r.slot} diverged")


def test_spec_serve_sampling_seed_deterministic():
    cfg, model, params = _setup(max_len=256)
    d_model, d_params = _draft_setup(cfg)
    prompts = _prompts(cfg, [6, 9])
    kw = dict(slots=2, max_new_tokens=8, temperature=0.8, top_p=0.9,
              draft=d_model, draft_params=d_params, spec_k=2)
    a = serve_loop(model, params, prompts, rng=jax.random.PRNGKey(4), **kw)
    b = serve_loop(model, params, prompts, rng=jax.random.PRNGKey(4), **kw)
    assert [r.tokens for r in a] == [r.tokens for r in b]
    assert all(0 <= t < cfg.vocab_size for r in a for t in r.tokens)


def test_spec_serve_validation():
    cfg, model, params = _setup(max_len=128)
    d_model, d_params = _draft_setup(cfg)
    prompts = _prompts(cfg, [5])
    with pytest.raises(ValueError, match="draft_params"):
        serve_loop(model, params, prompts, draft=d_model)
    with pytest.raises(ValueError, match="spec_k"):
        serve_loop(model, params, prompts, draft=d_model,
                   draft_params=d_params, spec_k=0)
    # windowed ring below the window + spec_k bound is refused
    w_cfg, w_model, w_params = _setup(max_len=256, sliding_window=8)
    wd_model, wd_params = _draft_setup(w_cfg)
    with pytest.raises(ValueError, match="window"):
        serve_loop(w_model, w_params, _prompts(w_cfg, [30]),
                   max_new_tokens=40, cache_len=9, draft=wd_model,
                   draft_params=wd_params, spec_k=4, prefill_chunk=3)


def test_spec_serve_default_cache_sizing_windowed():
    """128-multiple window + speculation with cache_len=None: the
    default sizing must include the spec_k ring slack its own
    validation demands (it previously refused its own choice: auto
    gave bucket(window)=128 while validation required window+spec_k).
    The ring genuinely wraps here (prompt+new exceeds the cache) and
    greedy output stays oracle-exact."""
    cfg, model, params = _setup(max_len=1024, sliding_window=128,
                                n_layers=1)
    d_model, d_params = _draft_setup(cfg)
    prompt = _prompts(cfg, [100])[0]
    res = serve_loop(model, params, [prompt], slots=1,
                     max_new_tokens=300, draft=d_model,
                     draft_params=d_params, spec_k=4, steps_per_sync=8)
    want = llama.generate(model, params, prompt[None, :], 300,
                          cache_len=256)
    assert res[0].tokens == [int(t) for t in np.asarray(want[0])]


def test_spec_serve_draft_smaller_max_len():
    """A draft whose max_len is smaller than the target's (and not a
    128-multiple) gets its own ring capped at ITS max_len instead of
    crashing in init_cache on the shared auto-sized value; outputs stay
    oracle-exact."""
    import dataclasses

    cfg, model, params = _setup(max_len=512)
    d_cfg = dataclasses.replace(cfg, n_layers=1, max_len=200)
    d_model = llama.Llama(d_cfg)
    d_params = d_model.init(jax.random.PRNGKey(9),
                            jnp.zeros((1, 8), jnp.int32),
                            train=False)["params"]
    prompt = _prompts(cfg, [100])[0]
    res = serve_loop(model, params, [prompt], slots=1,
                     max_new_tokens=90, draft=d_model,
                     draft_params=d_params, spec_k=4, steps_per_sync=4)
    want = llama.generate(model, params, prompt[None, :], 90)
    assert res[0].tokens == [int(t) for t in np.asarray(want[0])]


def test_prefill_budget_is_scheduling_not_semantics():
    """prefill_chunks_per_sync bounds admission stall; per-request
    tokens must be invariant to it (like steps_per_sync)."""
    cfg, model, params = _setup(max_len=256)
    prompts = _prompts(cfg, [40, 6, 33, 9])
    base = serve_loop(model, params, prompts, slots=2,
                      max_new_tokens=10, prefill_chunk=8)
    for budget in (1, 2, 100):
        got = serve_loop(model, params, prompts, slots=2,
                         max_new_tokens=10, prefill_chunk=8,
                         prefill_chunks_per_sync=budget)
        assert [r.tokens for r in got] == [r.tokens for r in base], budget


def test_prefill_budget_interleaves_with_decode():
    """The liveness property the budget exists for: while one lane
    streams a LONG prompt in 1-chunk installments, the other lane's
    short requests keep decoding — short requests finish before the
    long prefill even completes its admission."""
    cfg, model, params = _setup(max_len=512)
    long_p = _prompts(cfg, [200])[0]
    shorts = _prompts(cfg, [5, 6, 7], seed=3)
    prompts = [long_p] + shorts
    res = serve_loop(model, params, prompts, slots=2,
                     max_new_tokens=6, prefill_chunk=8,
                     prefill_chunks_per_sync=1, steps_per_sync=2)
    # outputs still oracle-exact
    for r, p in zip(res, prompts):
        assert r.tokens == _oracle(model, params, p, 6), r.slot
    # the long request (25 one-chunk installments, one per loop
    # iteration) was admitted LAST even though it was queued first —
    # every short request got its lane and finished before the long
    # prompt's streaming admission completed
    long_r, short_rs = res[0], res[1:]
    assert all(s.finished_at_step <= long_r.admitted_at_step
               for s in short_rs), (
        long_r, [s.finished_at_step for s in short_rs])


def test_prefill_budget_composes_with_speculation():
    cfg, model, params = _setup(max_len=512)
    d_model, d_params = _draft_setup(cfg)
    prompts = _prompts(cfg, [60, 7, 9])
    base = [_oracle(model, params, p, 8) for p in prompts]
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=8,
                     prefill_chunk=8, prefill_chunks_per_sync=2,
                     draft=d_model, draft_params=d_params, spec_k=2,
                     steps_per_sync=2)
    assert [r.tokens for r in res] == base


def test_prefill_budget_validation():
    cfg, model, params = _setup(max_len=128)
    p = _prompts(cfg, [5])
    for bad in (0, -1):
        with pytest.raises(ValueError, match="prefill_chunks_per_sync"):
            serve_loop(model, params, p, prefill_chunk=2,
                       prefill_chunks_per_sync=bad)


def test_spec_serve_reports_per_request_acceptance():
    """ServeResult carries each request's own accepted/proposed draft
    counts (overshoot rounds excluded); plain serving reports 0/0."""
    cfg, model, params = _setup(max_len=256)
    d_model, d_params = _draft_setup(cfg)
    prompts = _prompts(cfg, [6, 9, 4])
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=10,
                     draft=d_model, draft_params=d_params, spec_k=3,
                     steps_per_sync=2)
    for r in res:
        assert r.proposed_drafts > 0 and r.proposed_drafts % 3 == 0
        assert 0 <= r.accepted_drafts <= r.proposed_drafts
        # rounds made progress: each emits >= 1 token, so a request
        # cannot have proposed more rounds than tokens it emitted
        assert r.proposed_drafts // 3 <= len(r.tokens)
    plain = serve_loop(model, params, prompts, slots=2,
                       max_new_tokens=10)
    assert all(r.proposed_drafts == 0 and r.accepted_drafts == 0
               for r in plain)


def test_prefill_budget_requires_chunking():
    """A budget without prefill_chunk cannot bound anything (one-segment
    prefill) — refused rather than silently no-opped."""
    cfg, model, params = _setup(max_len=128)
    with pytest.raises(ValueError, match="needs prefill_chunk"):
        serve_loop(model, params, _prompts(cfg, [5]),
                   prefill_chunks_per_sync=1)


# ---------------------------------------------------------- prefix cache
def test_shared_prefix_equals_concatenated_prompts():
    """Prefix caching: serving suffixes with shared_prefix must emit
    exactly what serving the concatenated prompts emits (which equals
    isolated generate on prefix+suffix) — chunked and unchunked."""
    cfg, model, params = _setup(max_len=256)
    pfx = _prompts(cfg, [16])[0]
    sufs = _prompts(cfg, [5, 9, 3, 7], seed=4)
    full = [jnp.concatenate([pfx, s]) for s in sufs]
    for kw in ({}, {"prefill_chunk": 8},
               {"prefill_chunk": 8, "prefill_chunks_per_sync": 1}):
        res = serve_loop(model, params, sufs, slots=2,
                         max_new_tokens=10, shared_prefix=pfx, **kw)
        for r, f in zip(res, full):
            assert r.tokens == _oracle(model, params, f, 10), (kw, r.slot)


def test_shared_prefix_composes_with_speculation_and_int8kv():
    cfg, model, params = _setup(max_len=256)
    d_model, d_params = _draft_setup(cfg)
    pfx = _prompts(cfg, [8])[0]
    sufs = _prompts(cfg, [6, 4], seed=5)
    res = serve_loop(model, params, sufs, slots=2, max_new_tokens=8,
                     shared_prefix=pfx, kv_quant=True,
                     draft=d_model, draft_params=d_params, spec_k=2)
    for r, s in zip(res, sufs):
        f = jnp.concatenate([pfx, s])[None, :]
        want = llama.generate(model, params, f, 8, kv_quant=True)
        assert r.tokens == [int(t) for t in np.asarray(want[0])]


def test_shared_prefix_validation():
    cfg, model, params = _setup(max_len=256)
    sufs = _prompts(cfg, [5])
    pfx = _prompts(cfg, [10])[0]
    # misaligned prefix with chunking is refused, not silently unshared
    with pytest.raises(ValueError, match="multiple of"):
        serve_loop(model, params, sufs, shared_prefix=pfx,
                   prefill_chunk=8)
    with pytest.raises(ValueError, match="non-empty"):
        serve_loop(model, params, sufs,
                   shared_prefix=jnp.zeros((0,), jnp.int32))
    with pytest.raises(ValueError, match="suffix token"):
        serve_loop(model, params, [jnp.zeros((0,), jnp.int32)],
                   shared_prefix=pfx)


def test_shared_prefix_windowed_ring():
    """Prefix caching under a sliding-window model, BOTH prefill paths:
    unchunked (the two-segment prefix-write + suffix-fill split) and
    chunked through an O(window) ring."""
    cfg, model, params = _setup(max_len=256, sliding_window=8,
                                n_layers=2)
    pfx = _prompts(cfg, [16])[0]
    sufs = _prompts(cfg, [6, 9], seed=7)
    for kw in ({"cache_len": 64},
               {"prefill_chunk": 8, "cache_len": 16}):
        res = serve_loop(model, params, sufs, slots=2,
                         max_new_tokens=8, shared_prefix=pfx, **kw)
        for r, s in zip(res, sufs):
            f = jnp.concatenate([pfx, s])[None, :]
            want = llama.generate(model, params, f, 8,
                                  cache_len=kw.get("cache_len"),
                                  prefill_chunk=kw.get("prefill_chunk"))
            assert r.tokens == [int(t) for t in np.asarray(want[0])], kw


def test_randomized_feature_combinations_stay_oracle_exact():
    """Seeded property sweep: random slots/chunking/budget/prefix/
    speculation/window/int8 combinations, every one oracle-exact per
    request.  The grid tests above pin each feature's contract; this
    sweeps the CROSS-PRODUCT corners no hand-written case covers."""
    import dataclasses
    import random as pyrandom

    from tf_operator_tpu.models import quant

    rnd = pyrandom.Random(1234)
    base = _f32(max_len=256)
    w_cfg = _f32(max_len=256, sliding_window=8)
    for trial in range(6):
        windowed = rnd.random() < 0.5
        cfg = w_cfg if windowed else base
        model = llama.Llama(cfg)
        params = model.init(jax.random.PRNGKey(trial),
                            jnp.zeros((1, 8), jnp.int32),
                            train=False)["params"]
        int8 = rnd.random() < 0.4
        xform = None
        p_use = params
        if int8:
            p_use = quant.quantize_params(params)
            xform = quant.make_dequantizer(cfg.dtype)
        kv_q = rnd.random() < 0.4
        chunk = rnd.choice([None, 4, 8])
        kw = dict(slots=rnd.choice([1, 2, 3]),
                  max_new_tokens=rnd.choice([5, 9]),
                  steps_per_sync=rnd.choice([1, 3, 5]),
                  kv_quant=kv_q, params_transform=xform)
        if chunk is not None:
            kw["prefill_chunk"] = chunk
            if rnd.random() < 0.5:
                kw["prefill_chunks_per_sync"] = rnd.choice([1, 2])
        pfx = None
        if chunk is not None and rnd.random() < 0.5:
            pfx = _prompts(cfg, [chunk * rnd.choice([1, 2])],
                           seed=100 + trial)[0]
            kw["shared_prefix"] = pfx
        if rnd.random() < 0.5:
            d_cfg = dataclasses.replace(cfg, n_layers=1)
            d_model = llama.Llama(d_cfg)
            d_params = d_model.init(jax.random.PRNGKey(50 + trial),
                                    jnp.zeros((1, 8), jnp.int32),
                                    train=False)["params"]
            if int8:
                d_params = quant.quantize_params(d_params)
                kw["draft_transform"] = xform
            kw.update(draft=d_model, draft_params=d_params,
                      spec_k=rnd.choice([1, 2, 3]))
        lens = [rnd.randint(3, 14) for _ in range(rnd.randint(2, 4))]
        sufs = _prompts(cfg, lens, seed=200 + trial)
        res = serve_loop(model, p_use, sufs, **kw)
        for r, s in zip(res, sufs):
            f = (jnp.concatenate([pfx, s]) if pfx is not None else s)
            want = llama.generate(
                model, p_use, f[None, :], kw["max_new_tokens"],
                kv_quant=kv_q, params_transform=xform)
            assert r.tokens == [int(t) for t in np.asarray(want[0])], (
                trial, kw.keys(), r.slot)
