"""One cluster, one day (ISSUE 18): the mixed train+serve tenancy
harness with its compressed chaos day.

Late-alphabet file per the tier-1 870s-cap discipline: everything here
is SimClock-driven (no real sleeps).  The full-day configuration lives
in the bench (`make bench-cluster` -> BENCH_r16.json); these tests run
a COMPRESSED day — same chaos sequence (scrape storm, replica freeze,
kill-mid-decode, scheduler kill -9 + resync, node drain + uncordon),
smaller trace, shorter horizon — so the whole file stays well under
the fast-lane budget.
"""
from tf_operator_tpu.engine.clustersim import (
    ChaosDay, ClusterDaySim, GangSpec, run_cluster_day,
)


# ------------------------------------------------------------ compressed day
# Packed placement puts serve-r0 on n0, train-high (1x8) on n1 and
# train-low (2x8) on n2+n3 — the drain at t=75 lands on the high gang.
SMOKE = dict(
    nodes=4,
    n_users=60,
    trace_horizon_s=80.0,
    horizon_s=140.0,
    base_rate=1.0,
    burst_rate=7.0,
    bursts=((20.0, 12.0),),
    gangs=[
        GangSpec("train-high", replicas=1, priority=100, submit_at=0.5),
        GangSpec("train-low", replicas=2, priority=10,
                 min_replicas=1, submit_at=1.0),
    ],
    chaos=ChaosDay(
        scrape_storm_at=30.0, scrape_storm_s=6.0,
        freeze_at=40.0, kill_decode_at=46.0,
        blackout_at=55.0, blackout_s=10.0,
        drain_at=75.0, drain_node="n1", uncordon_at=90.0,
    ),
)


def run_smoke(hardened, seed=0):
    return run_cluster_day(seed=seed, hardened=hardened, **SMOKE)


def test_hardened_day_serves_everything_and_recovers_every_gang():
    """The headline contract on the compressed day: the hardened stack
    (shrink-before-evict + hedging + ejection) drops NOTHING through
    the whole chaos sequence, and every gang is back to Running at the
    horizon with restart counters matching the chaos ledger exactly
    (every death observed through the pods was booked by an injector —
    no unexplained restarts, no unobserved kills)."""
    r = run_smoke(hardened=True)
    s = r["serving"]
    assert s["dropped"] == 0
    assert s["completed"] == r["requests"] > 0
    # the frozen replica's trapped requests came back via hedging
    assert s["hedges_issued"] >= 1
    assert s["hedges_won"] >= 1
    # the day actually contained its chaos
    assert r["chaos"]["blackouts"] == 1
    for g in r["gangs"]:
        assert g["state"] == "running", g
        assert g["restarts_observed"] == g["restarts_booked"], g
        assert g["time_to_running_s"] is not None
    by = {g["name"]: g for g in r["gangs"]}
    # the drain hit the high gang: it restarted and recovered with a
    # measured MTTR on its flight-recorder timeline
    assert by["train-high"]["restarts_observed"] >= 1
    assert by["train-high"]["last_restart_mttr_s"] is not None


def test_baseline_day_measurably_loses():
    """Same seed, same trace, same chaos — hardening off.  The frozen
    replica heartbeats healthily forever, so without hedging its
    trapped requests are lost; without shrink-before-evict the serving
    spike evicts training whole instead of resizing it."""
    r = run_smoke(hardened=False)
    assert r["serving"]["dropped"] > 0
    assert r["serving"]["hedges_issued"] == 0
    # censored tail: the p99 rank lands in the lost region
    hard = run_smoke(hardened=True)
    assert hard["serving"]["completed"] > r["serving"]["completed"]


def test_day_is_byte_deterministic_per_seed():
    """The whole day — injector log, scheduler notes, router log — is a
    pure function of the seed: the transcript hash is identical across
    runs and differs across seeds."""
    a = run_smoke(hardened=True)
    b = run_smoke(hardened=True)
    assert a["log_sha256"] == b["log_sha256"]
    assert a["serving"]["completed"] == b["serving"]["completed"]
    c = run_smoke(hardened=True, seed=1)
    assert c["log_sha256"] != a["log_sha256"]
    # the two arms share the trace but not the transcript
    d = run_smoke(hardened=False)
    assert d["log_sha256"] != a["log_sha256"]


def test_serving_yields_to_pending_gang_exactly_once():
    """Satellite 3 (APF semantics at the capacity gate): a serving
    scale-out that wants chips a pending same-or-higher-priority gang
    needs loses to the gang exactly once — one serve_yield, one
    out_denied event, a full out-cooldown (no per-tick flapping) — and
    the NEXT attempt succeeds on inventory the finished tenant freed.

    Timeline (all deterministic per seed): batch (2x8, prio 100,
    finishes ~t=7.4) holds n1+n2; train-high (2x8, prio 100) parks
    pending from t=2 — same priority, so no preemption; a t=3 burst
    drives queue-wait p99 over the scale-out threshold; the autoscaler
    fires at t=7.3 while the gang is still pending -> yield; batch
    completes, the gang admits n1+n2; the t=8.3 retry lands serve-r1
    on n3."""
    sim = ClusterDaySim(
        seed=7, hardened=True, nodes=4, serve_max_replicas=2,
        requeue_backoff_s=0.25,
        gangs=[
            GangSpec("batch", replicas=2, priority=100,
                     submit_at=0.0, work_s=6.0),
            GangSpec("train-high", replicas=2, priority=100,
                     submit_at=2.0),
        ],
        n_users=40, trace_horizon_s=30.0, horizon_s=60.0,
        base_rate=0.5, burst_rate=12.0, bursts=((3.0, 2.0),),
        chaos=None,
    )
    r = sim.run()
    yields = [l for l in sim.inj.log if "serve_yield" in l]
    assert len(yields) == 1, yields
    assert "gang=default/train-high" in yields[0]
    denied = [e for e in sim.fleet.scale_events if e["dir"] == "out_denied"]
    assert len(denied) == 1
    assert r["serving"]["scale_out_denied"] == 1
    # the yield did not wedge the autoscaler: the retry after the
    # cooldown succeeded, and it waited at least the full cooldown
    outs = [e for e in sim.fleet.scale_events if e["dir"] == "out"]
    assert len(outs) == 1
    assert outs[0]["t"] - denied[0]["t"] >= 1.0 - 1e-9
    # ...and the gang it yielded to actually won the inventory
    by = {g["name"]: g for g in r["gangs"]}
    assert by["train-high"]["state"] == "running"
    assert by["batch"]["state"] == "done"
    # no eviction anywhere: the gate yielded instead of preempting
    assert by["train-high"]["restarts_observed"] == 0
    assert by["batch"]["restarts_observed"] == 0


def test_blackout_preserves_running_work_and_resyncs():
    """kill -9 of the scheduler alone (no other chaos): pods keep
    running through the blackout (the kubelet is alive), the respawn
    rebuilds every reservation from pod annotations + owner CRs, and
    the day ends with zero restarts anywhere — a control-plane death
    with no data-plane fault must cost nothing."""
    r = run_cluster_day(
        seed=3, hardened=True, nodes=4,
        n_users=30, trace_horizon_s=40.0, horizon_s=80.0,
        base_rate=1.0, burst_rate=3.0, bursts=(),
        gangs=[
            GangSpec("train-high", replicas=1, priority=100,
                     submit_at=0.5),
            GangSpec("train-low", replicas=2, priority=10,
                     min_replicas=1, submit_at=1.0),
        ],
        chaos=ChaosDay(
            scrape_storm_at=None, freeze_at=None, kill_decode_at=None,
            blackout_at=20.0, blackout_s=8.0,
            drain_at=None, uncordon_at=None,
        ),
    )
    assert r["chaos"]["blackouts"] == 1
    assert r["serving"]["dropped"] == 0
    for g in r["gangs"]:
        assert g["state"] == "running", g
        assert g["restarts_observed"] == 0, g
        assert g["restarts_booked"] == 0, g
