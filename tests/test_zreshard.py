"""Checkpoint resharding across mesh shapes (models/reshard.py) and the
elastic-resize loss-trajectory contract: train -> drain (final SIGTERM
checkpoint) -> reshard to a different mesh -> resume must match a
fixed-size golden run step for step — exact step count, loss within
float-reassociation tolerance.

Named late in the alphabet on purpose: jax compilation makes this file
heavy relative to the tier-1 870s cap; it runs in full suites.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tf_operator_tpu.models.reshard import (
    host_gather,
    place_state,
    reshard_checkpoint,
    reshard_shapes,
    state_shardings,
)
from tf_operator_tpu.parallel.mesh import make_mesh
from tf_operator_tpu.runtime.loop import PreemptionGuard, run_training
from tf_operator_tpu.runtime.train import (
    Checkpointer,
    create_train_state,
    make_train_step,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 4, reason="needs >= 4 (forced-host) devices"
)

D_IN, D_HID, D_OUT = 256, 128, 8  # w1 is 256x128 = 32768 > min_size


class _Mlp:
    """Two-layer MLP big enough that w1/w2 cross the fsdp min_size."""

    def init(self, rng, x, train=False):
        k1, k2 = jax.random.split(rng)
        scale = 0.05
        return {"params": {
            "w1": scale * jax.random.normal(k1, (D_IN, D_HID)),
            "b1": jnp.zeros(D_HID),
            "w2": scale * jax.random.normal(k2, (D_HID, D_OUT * 32)),
            "b2": jnp.zeros(D_OUT * 32),
        }}

    def apply(self, variables, x, train=False):
        p = variables["params"]
        h = jax.nn.relu(x @ p["w1"] + p["b1"])
        return (h @ p["w2"] + p["b2"])[:, :D_OUT]


def _mesh(fsdp):
    return make_mesh({"fsdp": fsdp}, jax.devices()[:fsdp])


def _fresh_state(mesh):
    state = create_train_state(
        jax.random.PRNGKey(0), _Mlp(), jnp.ones((8, D_IN)),
        optax.adam(1e-2),
    )
    return place_state(state, mesh)


def _setup(mesh):
    """(state, recording step fn, losses) with the pjit contract wired:
    out_shardings come from state_shardings of the EXACT state instance
    being trained (TrainState's tx rides the pytree aux, so shardings
    built from a different instance would not match the traced tree)."""
    state = _fresh_state(mesh)
    losses = []
    inner = make_train_step(
        _Mlp(), has_batch_stats=False, mesh=mesh,
        state_shardings=state_shardings(state, mesh),
    )

    def step(s, x, y):
        s, m = inner(s, x, y)
        losses.append(float(m["loss"]))
        return s, m

    return state, step, losses


def _batches(start=0, n=64):
    """Deterministic per-step batches so two runs (resized or not) feed
    identical data at identical step numbers."""
    for i in range(start, start + n):
        k = jax.random.PRNGKey(1000 + i)
        kx, ky = jax.random.split(k)
        yield (
            jax.random.normal(kx, (8, D_IN)),
            jax.random.randint(ky, (8,), 0, D_OUT),
        )


# ----------------------------------------------------------- placement
def test_state_shardings_shards_large_leaves_and_replicates_small():
    mesh = _mesh(4)
    state = _fresh_state(mesh)
    sh = state_shardings(state, mesh)
    w1 = sh.params["w1"].spec
    assert "fsdp" in tuple(w1), w1          # large: sharded
    assert tuple(sh.params["b1"].spec) in ((), (None,)), (
        sh.params["b1"].spec)               # small: replicated
    # adam moments shaped like w1 shard exactly like w1 — the optimizer
    # state rides the same single placement rule
    mu_w1 = jax.tree.leaves(
        jax.tree.map(lambda s: s, sh.opt_state),
    )
    assert any("fsdp" in tuple(getattr(s, "spec", ())) for s in mu_w1)


def test_reshard_checkpoint_grow_shrink_and_crash_rerun(tmp_path):
    mesh2, mesh4 = _mesh(2), _mesh(4)
    state = _fresh_state(mesh2)
    state = state.replace(step=jnp.asarray(9, jnp.int32))
    ck = Checkpointer(str(tmp_path / "src"))
    ck.save(9, state)

    dst = str(tmp_path / "dst")
    step = reshard_checkpoint(str(tmp_path / "src"), dst, mesh4)
    assert step == 9
    # crash-rerun idempotency: the destination is scratch until the
    # phase machine advances — a second run overwrites, same result
    assert reshard_checkpoint(str(tmp_path / "src"), dst, mesh4) == 9

    template = place_state(_fresh_state(mesh4), mesh4)
    restored = Checkpointer(dst).restore(template)
    assert int(restored.step) == 9
    for k in ("w1", "b1", "w2", "b2"):
        np.testing.assert_array_equal(
            np.asarray(restored.params[k]), np.asarray(state.params[k])
        )
    # and back down: 4 -> 2 (the shrink-before-evict direction)
    dst2 = str(tmp_path / "dst2")
    assert reshard_checkpoint(dst, dst2, mesh2) == 9
    back = Checkpointer(dst2).restore(place_state(_fresh_state(mesh2), mesh2))
    np.testing.assert_array_equal(
        np.asarray(back.params["w1"]), np.asarray(state.params["w1"])
    )


def test_reshard_refuses_in_place_destination(tmp_path):
    with pytest.raises(ValueError, match="distinct"):
        reshard_checkpoint(str(tmp_path), str(tmp_path), _mesh(2))


def test_reshard_without_checkpoint_raises(tmp_path):
    os.makedirs(tmp_path / "empty", exist_ok=True)
    with pytest.raises(ValueError, match="no checkpoint"):
        reshard_checkpoint(
            str(tmp_path / "empty"), str(tmp_path / "out"), _mesh(2)
        )


def test_host_gather_materializes_numpy():
    mesh = _mesh(2)
    state = _fresh_state(mesh)
    host = host_gather({"params": state.params})
    assert all(
        isinstance(x, np.ndarray) for x in jax.tree.leaves(host)
    )


def test_reshard_shapes_summary():
    s = reshard_shapes({"Worker": 4}, {"Worker": 2})
    assert s["direction"] == "shrink"
    assert s["types"]["Worker"] == [4, 2]
    assert reshard_shapes({"Worker": 2}, {"Worker": 4})["direction"] == "grow"


# ------------------------------------------------- drain step exactness
def test_drain_saves_the_exact_inflight_step(tmp_path):
    """SIGTERM mid-run: the final checkpoint holds exactly the step the
    loop reached — the resharded resume loses at most the in-flight
    step, never a save interval (LoopResult.last_saved_step contract)."""
    mesh = _mesh(2)
    state, step, losses = _setup(mesh)
    guard = PreemptionGuard(install=False)

    def batches():
        for i, b in enumerate(_batches()):
            if i == 7:
                guard.trigger()  # SIGTERM lands between steps 7 and 8
            yield b

    ck = Checkpointer(str(tmp_path / "ck"))
    res = run_training(
        state, step, batches(),
        num_steps=50, checkpointer=ck, save_interval_steps=100,
        guard=guard,
    )
    assert res.preempted
    assert res.steps_run == 8
    assert res.last_saved_step == 8
    assert ck.latest_step() == 8


# ------------------------------------------------------ loss trajectory
def test_loss_trajectory_resize_matches_fixed_size_golden(tmp_path):
    """train 6 steps @ fsdp=2 -> drain -> reshard -> resume @ fsdp=4 for
    6 more; the resumed trajectory must match a never-resized fsdp=4 run
    fed identical batches — same steps, same losses (float tolerance).
    in/out axis_resources ride state_shardings on BOTH sides of the
    boundary, so no hidden cross-boundary resharding can skew step one
    after the resume (the SNIPPETS.md pjit contract)."""
    mesh_small, mesh_big = _mesh(2), _mesh(4)

    g_state, g_step, golden = _setup(mesh_big)
    run_training(g_state, g_step, _batches(start=0), num_steps=12)
    assert len(golden) == 12

    # elastic leg 1: the old shape, drained at step 6 with a final save
    s_state, s_step, leg1 = _setup(mesh_small)
    src = str(tmp_path / "src")
    res1 = run_training(
        s_state, s_step, _batches(start=0), num_steps=6,
        checkpointer=Checkpointer(src), save_interval_steps=3,
    )
    assert res1.last_saved_step == 6
    np.testing.assert_allclose(leg1, golden[:6], rtol=2e-4, atol=1e-5)

    # reshard: old sharding -> host gather -> new mesh's shardings
    dst = str(tmp_path / "dst")
    assert reshard_checkpoint(src, dst, mesh_big) == 6

    # elastic leg 2: resume on the NEW mesh from the resharded step
    r_state, r_step, leg2 = _setup(mesh_big)
    res2 = run_training(
        r_state, r_step, _batches(start=6), num_steps=12,
        checkpointer=Checkpointer(dst), save_interval_steps=100,
    )
    assert res2.resumed_from == 6          # exact step count preserved
    assert int(res2.state.step) == 12
    assert len(leg2) == 6
    # re-warmup: the resumed run re-traces/compiles, but numerically it
    # must track the fixed-size golden from its very first step
    np.testing.assert_allclose(leg2, golden[6:], rtol=2e-4, atol=1e-5)
