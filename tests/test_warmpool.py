"""Warm-pool pod placement — claims, replenishment, contention, fencing.

The ISSUE 7 acceptance surface: the pool keeps K pre-provisioned standby
pods per slice shape; job pod creation claims one with a fenced CAS (under
contention exactly one claimer wins, the loser's expectations are never
touched); replenishment rides the slow-start fan-out behind a retry ladder
and never overshoots K; and the whole subsystem is off (byte-identical
engine) at the default --warm-pool-size 0.
"""
import json

import pytest

from tf_operator_tpu.api import common
from tf_operator_tpu.cmd.manager import OperatorManager, ShardedOperator, build_warm_pool
from tf_operator_tpu.cmd.options import ServerOptions, parse_args
from tf_operator_tpu.controllers.registry import EnabledSchemes, make_engine
from tf_operator_tpu.engine import metrics, warmpool
from tf_operator_tpu.engine.sharding import fence_token
from tf_operator_tpu.engine.warmpool import (
    DEFAULT_SHAPE,
    WARM_POOL_LABEL,
    WarmPoolConfig,
    WarmPoolManager,
)
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.chaos import FaultInjector, SimClock
from tf_operator_tpu.k8s.fake import ApiError, FakeCluster, StaleFencingTokenError

from tests import testutil
from tests.test_engine import reconcile


def make_pool(cluster, sizes=None, clock=None, **cfg):
    return WarmPoolManager(
        cluster,
        WarmPoolConfig(sizes=sizes or {DEFAULT_SHAPE: 3}, **cfg),
        clock=clock or (lambda: 0.0),
    )


def mark_pool_running(cluster):
    """What the kubelet does after image pull + runtime init."""
    for pod in cluster.list_pods():
        if WARM_POOL_LABEL in objects.labels_of(pod) and (
            objects.pod_phase(pod) != objects.POD_RUNNING
        ):
            pod["status"]["phase"] = objects.POD_RUNNING
            cluster.update_pod(pod)


def pool_engine(cluster, pool, kind="TFJob"):
    engine = make_engine(kind, cluster)
    engine.warm_pool = pool
    return engine


def submit(cluster, job):
    cluster.create(job.kind, job.to_dict())
    return job


# ------------------------------------------------------------- replenishment
def test_pool_fills_to_k_per_shape_and_never_overshoots():
    cluster = FakeCluster()
    pool = make_pool(cluster, sizes={"v5e-1": 3, "v5e-8": 2})
    assert pool.replenish() == 5
    pods = cluster.list_pods()
    assert len(pods) == 5
    by_shape = {}
    for p in pods:
        by_shape.setdefault(
            objects.labels_of(p)[WARM_POOL_LABEL], []
        ).append(p)
        # unowned until claimed: failover and GC must both ignore them
        assert objects.get_controller_of(p) is None
    assert {s: len(v) for s, v in by_shape.items()} == {"v5e-1": 3, "v5e-8": 2}
    # filling, not ready, until the kubelet marks them Running
    assert pool.ready_count("v5e-1") == 0
    mark_pool_running(cluster)
    assert pool.ready_count("v5e-1") == 3
    # idempotent: a full pool creates nothing
    assert pool.replenish() == 0
    assert len(cluster.list_pods()) == 5


def test_pool_resync_adopts_survivors_and_advances_seq():
    """Operator restart: a fresh pool over the same cluster re-adopts the
    unclaimed standby pods instead of leaking them and creating K more."""
    cluster = FakeCluster()
    make_pool(cluster).replenish()
    mark_pool_running(cluster)
    pool2 = make_pool(cluster)
    pool2.resync()
    assert pool2.size(DEFAULT_SHAPE) == 3
    assert pool2.replenish() == 0
    assert len(cluster.list_pods()) == 3
    # new names never collide with survivors
    pool2._pool[DEFAULT_SHAPE].popitem()
    assert pool2.replenish() == 1
    names = {objects.name_of(p) for p in cluster.list_pods()}
    assert len(names) == 4


def test_replenish_survives_api_error_storm_with_retry_ladder():
    """A create storm: the slow-start ramp probes with ONE create per
    attempt, the per-shape ladder spaces attempts out exponentially, and
    the pool converges to exactly K after the storm — never past it."""
    inner = FakeCluster()
    clock = SimClock()
    inj = FaultInjector(inner, seed=7, clock=clock, kubelet=False)
    inj.schedule_storm(0, 100, fault="500", ops=["create"], kinds=["Pod"])
    inj.step(1.0)  # enter the storm
    pool = WarmPoolManager(
        inj, WarmPoolConfig(sizes={DEFAULT_SHAPE: 4}), clock=clock
    )
    attempts_in_storm = 0
    for _ in range(99):
        before = inj.stats.get("fault.500", 0)
        pool.replenish()
        attempts_in_storm += inj.stats.get("fault.500", 0) - before
        inj.step(1.0)
    # 99 replenish calls inside the storm but the ladder gated most and
    # the slow-start probe kept each attempt to a single doomed create
    assert 0 < attempts_in_storm <= 10, (attempts_in_storm, inj.stats)
    assert inner.list_pods() == []
    # storm over (t>100): ladder expires, pool converges to exactly K
    for _ in range(70):
        pool.replenish()
        inj.step(1.0)
    assert len(inner.list_pods()) == 4
    assert pool.size(DEFAULT_SHAPE) == 4


# ------------------------------------------------------------------- claims
def test_claim_binds_identity_and_keeps_ledger_exact():
    cluster = FakeCluster()
    pool = make_pool(cluster)
    pool.replenish()
    mark_pool_running(cluster)
    engine = pool_engine(cluster, pool)
    claims0 = metrics.WARM_POOL_CLAIMS.get({"shape": DEFAULT_SHAPE})
    job = submit(cluster, testutil.new_tfjob("wj", worker=2))
    job, result = reconcile(cluster, engine, job)
    assert result.error is None
    assert metrics.WARM_POOL_CLAIMS.get({"shape": DEFAULT_SHAPE}) - claims0 == 2
    job_pods = sorted(
        (p for p in cluster.list_pods()
         if objects.labels_of(p).get(objects.LABEL_JOB_NAME) == "wj"),
        key=lambda p: objects.labels_of(p)[objects.LABEL_REPLICA_INDEX],
    )
    assert len(job_pods) == 2
    for i, pod in enumerate(job_pods):
        labels = objects.labels_of(pod)
        # full replica identity in one CAS write
        assert labels[objects.LABEL_REPLICA_TYPE] == "worker"
        assert labels[objects.LABEL_REPLICA_INDEX] == str(i)
        assert labels[WARM_POOL_LABEL] == DEFAULT_SHAPE  # provenance kept
        ref = objects.get_controller_of(pod)
        assert ref and ref["uid"] == job.uid
        ann = pod["metadata"]["annotations"]
        assert ann[warmpool.WARM_BOUND_NAME_ANNOTATION] == f"wj-worker-{i}"
        # the TF_CONFIG late-binding contract rides in the annotation
        env = json.loads(ann[warmpool.WARM_BOUND_ENV_ANNOTATION])
        assert any(e["name"] == "TF_CONFIG" for e in env)
        # claimed pod was already Running: the cold start never happened
        assert objects.pod_phase(pod) == objects.POD_RUNNING
    # a claim raises and settles the same ledger entry a create would
    assert engine.satisfied_expectations(job)
    assert engine._pending_claims == {}
    # the next sync (the claim MODIFIED re-enqueues the job in the real
    # manager) counts the already-Running replicas immediately — no
    # kubelet round trip ever happens for them
    job, _ = reconcile(cluster, engine, job)
    status = common.JobStatus.from_dict(
        cluster.get("TFJob", "default", "wj")["status"]
    )
    assert status.replica_statuses["Worker"].active == 2
    assert pool.size(DEFAULT_SHAPE) == 1


def test_empty_pool_misses_and_cold_creates():
    cluster = FakeCluster()
    pool = make_pool(cluster, sizes={DEFAULT_SHAPE: 0})
    engine = pool_engine(cluster, pool)
    misses0 = metrics.WARM_POOL_CLAIM_MISSES.get(
        {"shape": DEFAULT_SHAPE, "reason": "empty"}
    )
    job = submit(cluster, testutil.new_tfjob("cold", worker=1))
    job, result = reconcile(cluster, engine, job)
    assert result.error is None
    assert metrics.WARM_POOL_CLAIM_MISSES.get(
        {"shape": DEFAULT_SHAPE, "reason": "empty"}
    ) - misses0 == 1
    pods = cluster.list_pods()
    assert len(pods) == 1
    assert objects.name_of(pods[0]) == "cold-worker-0"  # cold path naming
    assert engine.satisfied_expectations(job)
    assert engine._pending_claims == {}


def test_strict_image_matching_misses_on_mismatch():
    cluster = FakeCluster()
    pool = make_pool(cluster, image="prewarmed:v1", match_any_image=False)
    pool.replenish()
    mark_pool_running(cluster)
    engine = pool_engine(cluster, pool)
    job = submit(cluster, testutil.new_tfjob("mm", worker=1))
    job, _ = reconcile(cluster, engine, job)
    # testutil's image != prewarmed:v1 → no pre-pull win, cold create
    assert metrics.WARM_POOL_CLAIM_MISSES.get(
        {"shape": DEFAULT_SHAPE, "reason": "image_mismatch"}
    ) >= 1
    assert pool.size(DEFAULT_SHAPE) == 3
    assert any(
        objects.name_of(p) == "mm-worker-0" for p in cluster.list_pods()
    )


def test_pool_pods_only_claimable_once_ready():
    """A Pending standby is still paying pull/init — claiming it would
    inherit the cold start, so it is not claimable."""
    cluster = FakeCluster()
    pool = make_pool(cluster)
    pool.replenish()  # all Pending
    engine = pool_engine(cluster, pool)
    job = submit(cluster, testutil.new_tfjob("pend", worker=1))
    reconcile(cluster, engine, job)
    assert metrics.WARM_POOL_CLAIMS.get({"shape": DEFAULT_SHAPE}) == 0 or (
        not any(
            objects.labels_of(p).get(objects.LABEL_JOB_NAME) == "pend"
            and WARM_POOL_LABEL in objects.labels_of(p)
            for p in cluster.list_pods()
        )
    )
    assert any(
        objects.name_of(p) == "pend-worker-0" for p in cluster.list_pods()
    )


def test_contested_claim_exactly_one_wins_and_loser_ledger_untouched():
    """Two operator processes (two pools, two engines) race for the same
    warm pod: the resourceVersion CAS lets exactly one claim land; the
    loser's conflict re-reads, sees the rival's controllerRef, falls back
    to a cold create, and its expectations ledger stays exact."""
    cluster = FakeCluster()
    pool_a = make_pool(cluster, sizes={DEFAULT_SHAPE: 1})
    pool_a.replenish()
    mark_pool_running(cluster)
    pool_b = make_pool(cluster, sizes={DEFAULT_SHAPE: 1})
    pool_b.resync()  # both processes track the SAME single warm pod
    assert pool_b.ready_count(DEFAULT_SHAPE) == 1

    engine_a = pool_engine(cluster, pool_a)
    engine_b = pool_engine(cluster, pool_b)
    job_a = submit(cluster, testutil.new_tfjob("race-a", worker=1))
    job_b = submit(cluster, testutil.new_tfjob("race-b", worker=1))

    # snapshot B's view BEFORE A claims: a separate process would not
    # have seen the claim MODIFIED yet, so its tracked copy still shows
    # the pod unclaimed at the pre-claim resourceVersion
    stale = objects.fast_deepcopy(
        next(iter(pool_b._pool[DEFAULT_SHAPE].values()))
    )
    job_a, res_a = reconcile(cluster, engine_a, job_a)
    assert res_a.error is None
    pool_b._pool[DEFAULT_SHAPE] = {objects.name_of(stale): stale}
    job_b, res_b = reconcile(cluster, engine_b, job_b)
    assert res_b.error is None

    pods = cluster.list_pods()
    warm_claimed = [
        p for p in pods
        if WARM_POOL_LABEL in objects.labels_of(p)
        and objects.get_controller_of(p) is not None
    ]
    assert len(warm_claimed) == 1
    assert objects.get_controller_of(warm_claimed[0])["uid"] == job_a.uid
    # the loser cold-created; no pod serves two masters, no index doubled
    b_pods = [
        p for p in pods
        if objects.labels_of(p).get(objects.LABEL_JOB_NAME) == "race-b"
    ]
    assert len(b_pods) == 1 and objects.name_of(b_pods[0]) == "race-b-worker-0"
    assert metrics.WARM_POOL_CLAIM_MISSES.get(
        {"shape": DEFAULT_SHAPE, "reason": "contested"}
    ) >= 1
    # both ledgers exact: the contested claim never touched B's
    assert engine_a.satisfied_expectations(job_a)
    assert engine_b.satisfied_expectations(job_b)
    assert engine_a._pending_claims == {} and engine_b._pending_claims == {}


def test_zombie_shard_claim_is_fenced():
    """A shard whose slot lease was taken over (generation bumped) must
    not claim warm pods for jobs it no longer owns: the store rejects the
    stale-token claim with 403 before it lands, the engine settles the
    raised expectation, and the pod stays unclaimed for the real owner."""
    cluster = FakeCluster()
    # the slot Lease the fence checks against, already at generation 2
    cluster.create("Lease", {
        "apiVersion": "coordination.k8s.io/v1", "kind": "Lease",
        "metadata": {"name": "tpu-operator-shard-0", "namespace": "default"},
        "spec": {"generation": 2},
    })
    pool = make_pool(cluster)
    pool.replenish()
    mark_pool_running(cluster)
    engine = pool_engine(cluster, pool)
    # the zombie still carries its pre-failover token (generation 1)
    engine.fence = lambda uid: fence_token("default", "tpu-operator-shard-0", 1)
    rejections0 = sum(metrics.FENCING_REJECTIONS.samples().values())
    job = submit(cluster, testutil.new_tfjob("zomb", worker=1))
    fresh = engine.adapter.from_dict(cluster.get("TFJob", "default", "zomb"))
    # the 403 escapes the sync (the fenced status-write fallback inside
    # the error path is fenced too, correctly) — _sync_guarded catches
    # exactly this class and disowns, which the chaos soak exercises
    with pytest.raises(StaleFencingTokenError):
        engine.reconcile(fresh)
    assert sum(metrics.FENCING_REJECTIONS.samples().values()) > rejections0
    # nothing claimed, nothing leaked: pod unclaimed, ledger settled
    assert pool.size(DEFAULT_SHAPE) in (2, 3)  # dropped locally at most
    assert all(
        objects.get_controller_of(p) is None for p in cluster.list_pods()
        if WARM_POOL_LABEL in objects.labels_of(p)
    )
    assert engine.satisfied_expectations(fresh)
    assert engine._pending_claims == {}


def test_disown_drops_pending_claims():
    cluster = FakeCluster()
    engine = make_engine("TFJob", cluster)
    engine._pending_claims["tok-1"] = ("exp", "default/moved")
    engine._pending_claims["tok-2"] = ("exp", "default/kept")
    engine.disown_job("default/moved")
    assert list(engine._pending_claims) == ["tok-2"]
    engine.forget_job("default/kept")
    assert engine._pending_claims == {}


# ------------------------------------------------------------------- wiring
def test_options_parse_warm_pool_flags():
    o = parse_args([
        "--warm-pool-size", "4",
        "--warm-pool-shape", "v5e-8=2",
        "--warm-pool-shape", "v5e-256=1",
        "--warm-pool-image", "prewarm:2",
        "--warm-pool-refill-interval", "0.1",
    ])
    assert o.warm_pool_size == 4
    assert o.warm_pool_shapes == {"v5e-8": 2, "v5e-256": 1}
    assert o.warm_pool_image == "prewarm:2"
    assert o.warm_pool_refill_interval == 0.1
    pool = build_warm_pool(FakeCluster(), o)
    assert pool.config.sizes == {"v5e-8": 2, "v5e-256": 1, DEFAULT_SHAPE: 4}
    # default: no pool, engine untouched
    assert build_warm_pool(FakeCluster(), parse_args([])) is None


def test_manager_wires_one_shared_pool_across_shards():
    cluster = FakeCluster()
    opts = ServerOptions(
        enabled_schemes=EnabledSchemes(["TFJob"]), warm_pool_size=2
    )
    sharded = ShardedOperator(cluster, opts, shard_count=4)
    assert sharded.warm_pool is not None
    engines = [
        s.manager.controllers["TFJob"].engine for s in sharded.shards
    ]
    assert all(e.warm_pool is sharded.warm_pool for e in engines)
    # single-process manager builds and owns its own
    mgr = OperatorManager(FakeCluster(), opts)
    assert mgr.warm_pool is not None and mgr._owns_warm_pool
    assert mgr.controllers["TFJob"].engine.warm_pool is mgr.warm_pool
    # disabled → None everywhere
    off = OperatorManager(
        FakeCluster(), ServerOptions(enabled_schemes=EnabledSchemes(["TFJob"]))
    )
    assert off.warm_pool is None
    assert off.controllers["TFJob"].engine.warm_pool is None


def test_slice_shape_selection():
    assert warmpool.slice_shape_of({"spec": {}}) == DEFAULT_SHAPE
    t = {"metadata": {"annotations": {warmpool.SHAPE_ANNOTATION: "v5e-256"}}}
    assert warmpool.slice_shape_of(t) == "v5e-256"
    t = {"metadata": {"labels": {warmpool.SHAPE_ANNOTATION: "v5e-8"}}}
    assert warmpool.slice_shape_of(t) == "v5e-8"


def test_shaped_job_claims_only_matching_shape():
    cluster = FakeCluster()
    pool = make_pool(cluster, sizes={"v5e-8": 1, DEFAULT_SHAPE: 1})
    pool.replenish()
    mark_pool_running(cluster)
    engine = pool_engine(cluster, pool)
    job = testutil.new_tfjob("shaped", worker=1)
    tmpl = job.replica_specs["Worker"].template
    tmpl.setdefault("metadata", {}).setdefault("annotations", {})[
        warmpool.SHAPE_ANNOTATION
    ] = "v5e-8"
    submit(cluster, job)
    reconcile(cluster, engine, job)
    assert pool.size("v5e-8") == 0  # the v5e-8 standby was claimed
    assert pool.size(DEFAULT_SHAPE) == 1  # the default-shape one was not


# -------------------------------------------------------------- e2e kubelet
def test_fake_kubelet_latency_sampling_is_seeded():
    from tf_operator_tpu.e2e.kubelet import FakeKubelet

    samples = []
    for _ in range(2):
        k = FakeKubelet(
            FakeCluster(), pull_delay=(0.5, 2.0), init_delay=0.25,
            latency_seed=42,
        )
        samples.append([k._startup_latency() for _ in range(4)])
    assert samples[0] == samples[1], "same seed must sample the same delays"
    assert all(0.75 <= s <= 2.25 for s in samples[0])


def test_warm_claims_satisfy_expectation_gate_before_any_cache_sync():
    """The claim's MODIFIED event settles the ledger the way a create's
    ADDED does — the next sync is never gated by a phantom expectation."""
    cluster = FakeCluster()
    pool = make_pool(cluster)
    pool.replenish()
    mark_pool_running(cluster)
    engine = pool_engine(cluster, pool)
    job = submit(cluster, testutil.new_tfjob("gate", worker=3))
    job, _ = reconcile(cluster, engine, job)
    # second sync runs (gate open) and is a no-op: no extra pods
    job, result = reconcile(cluster, engine, job)
    assert result.error is None
    n_job_pods = sum(
        1 for p in cluster.list_pods()
        if objects.labels_of(p).get(objects.LABEL_JOB_NAME) == "gate"
    )
    assert n_job_pods == 3


# ------------------------------------------------- review-round regressions
def test_claim_requires_matching_restart_policy():
    """Pod spec is immutable at claim time, so a standby (born Never) can
    only serve replicas whose EFFECTIVE policy is Never — an Always job
    claiming it would hand the kubelet the wrong in-place-restart
    behavior and hide container exits from the operator's accounting."""
    cluster = FakeCluster()
    pool = make_pool(cluster)
    pool.replenish()
    mark_pool_running(cluster)
    for p in cluster.list_pods():
        assert p["spec"]["restartPolicy"] == "Never"

    engine = pool_engine(cluster, pool)
    misses0 = metrics.WARM_POOL_CLAIM_MISSES.get(
        {"shape": DEFAULT_SHAPE, "reason": "restart_policy"}
    )
    job = testutil.new_tfjob("alw", worker=1)
    job.replica_specs["Worker"].restart_policy = common.RESTART_POLICY_ALWAYS
    job = submit(cluster, job)
    job, res = reconcile(cluster, engine, job)
    assert res.error is None
    # cold-created with the job's own policy; pool untouched
    pods = [
        p for p in cluster.list_pods()
        if objects.labels_of(p).get(objects.LABEL_JOB_NAME) == "alw"
    ]
    assert len(pods) == 1 and WARM_POOL_LABEL not in objects.labels_of(pods[0])
    assert pods[0]["spec"]["restartPolicy"] == "Always"
    assert pool.ready_count(DEFAULT_SHAPE) == 3
    assert metrics.WARM_POOL_CLAIM_MISSES.get(
        {"shape": DEFAULT_SHAPE, "reason": "restart_policy"}
    ) - misses0 == 1

    # ExitCode is rewritten to an effective Never before the claim: it
    # stays pool-eligible (the operator, not the kubelet, owns restarts)
    job2 = testutil.new_tfjob("exc", worker=1)
    job2.replica_specs["Worker"].restart_policy = (
        common.RESTART_POLICY_EXIT_CODE
    )
    job2 = submit(cluster, job2)
    job2, res2 = reconcile(cluster, engine, job2)
    assert res2.error is None
    pods2 = [
        p for p in cluster.list_pods()
        if objects.labels_of(p).get(objects.LABEL_JOB_NAME) == "exc"
    ]
    assert len(pods2) == 1 and WARM_POOL_LABEL in objects.labels_of(pods2[0])
    assert pods2[0]["spec"]["restartPolicy"] == "Never"


def test_relist_added_then_modified_settles_ledger_exactly_once():
    """Watch-outage repair can deliver a CLAIMED pod as ADDED (the claim's
    MODIFIED was swallowed by the gap).  The ADDED settles the expectation
    via the job labels AND must retire the pending claim token — otherwise
    the pod's next status MODIFIED (which still carries the persisted
    claim annotation) settles the same expectation again, driving the
    ledger's add-count negative and defeating the double-creation guard."""
    from tf_operator_tpu.engine.expectations import gen_expectation_pods_key

    cluster = FakeCluster()
    pool = make_pool(cluster, sizes={DEFAULT_SHAPE: 1})
    pool.replenish()
    mark_pool_running(cluster)
    engine = pool_engine(cluster, pool)
    # the outage: the engine's pod-event stream goes dark before the claim
    cluster.unsubscribe("Pod", engine._on_pod_event)
    job = submit(cluster, testutil.new_tfjob("relist", worker=1))
    job, res = reconcile(cluster, engine, job)
    assert res.error is None
    assert len(engine._pending_claims) == 1
    assert not engine.satisfied_expectations(job)
    claimed = next(
        p for p in cluster.list_pods()
        if objects.labels_of(p).get(objects.LABEL_JOB_NAME) == "relist"
    )
    # repair relist delivers the claimed pod as ADDED: settles + retires
    engine._on_pod_event("ADDED", claimed)
    assert engine.satisfied_expectations(job)
    assert engine._pending_claims == {}
    # a later kubelet status write must NOT settle a second time
    engine._on_pod_event("MODIFIED", claimed)
    key = gen_expectation_pods_key(job.key, "Worker")
    engine.expectations.expect_creations(key, 1)
    assert not engine.expectations.satisfied_expectations(key), (
        "add-count went negative: one outstanding creation reads satisfied"
    )


def test_pool_tracks_pods_surfacing_via_events_before_insert():
    """REST-backend race: the watch can deliver a standby's events before
    replenish's create call returns and inserts it.  Dropping unknown
    names would store a stale Pending copy (never claimable) and blind
    the deficit math into a duplicate create — the pool must adopt
    label-matching unclaimed pods straight off the event stream."""
    cluster = FakeCluster()
    pool = make_pool(cluster, sizes={DEFAULT_SHAPE: 1})
    # the pod surfaces via ADDED/MODIFIED only — never via create_one
    cluster.create_pod(pool._standby_pod(DEFAULT_SHAPE, "warm-v5e-1-99"))
    assert pool.size(DEFAULT_SHAPE) == 1
    mark_pool_running(cluster)  # MODIFIED upserts the Running copy
    assert pool.ready_count(DEFAULT_SHAPE) == 1
    # deficit math sees it: no duplicate create past K
    assert pool.replenish() == 0
    assert len(cluster.list_pods()) == 1


def test_replenish_reaps_terminal_standbys():
    """An unclaimed standby whose pre-warm runtime exited (or chaos
    OOM-killed) is dead weight: not claimable, yet counted by the deficit
    math.  Replenish deletes it and refills the slot."""
    cluster = FakeCluster()
    pool = make_pool(cluster)
    pool.replenish()
    mark_pool_running(cluster)
    corpse = cluster.list_pods()[0]
    corpse["status"]["phase"] = objects.POD_FAILED
    cluster.update_pod(corpse)
    assert pool.ready_count(DEFAULT_SHAPE) == 2
    assert pool.replenish() == 1
    pods = cluster.list_pods()
    assert len(pods) == 3
    assert all(
        objects.pod_phase(p) != objects.POD_FAILED for p in pods
    )
    mark_pool_running(cluster)
    assert pool.ready_count(DEFAULT_SHAPE) == 3


def test_claim_misses_counted_once_per_fallback_not_per_candidate():
    """docs/monitoring.md reads claim_misses_total as 'claims that fell
    back toward cold' — one fallback must count once, no matter how many
    candidates were scanned on the way."""
    cluster = FakeCluster()
    pool = make_pool(cluster)  # K=3, all in namespace "default"
    pool.replenish()
    mark_pool_running(cluster)
    before = metrics.WARM_POOL_CLAIM_MISSES.get(
        {"shape": DEFAULT_SHAPE, "reason": "namespace"}
    )
    out = pool.try_claim(
        namespace="other-ns", shape=DEFAULT_SHAPE, image="x",
        labels={}, annotations={},
        controller_ref={"kind": "TFJob", "name": "j", "uid": "u"},
    )
    assert out is None
    assert metrics.WARM_POOL_CLAIM_MISSES.get(
        {"shape": DEFAULT_SHAPE, "reason": "namespace"}
    ) - before == 1
