"""int8 KV cache (llama.init_cache kv_quant): K/V quantize at the cache
write with per-(position, head) scales, dequantize fused into the
attention read — the decode step's OTHER dominant HBM stream halved
(weights being the first, models/quant.py).  Unlike int8 weights the
output is approximate, so the witnesses here are error-BOUNDED logits
plus exact internal-consistency contracts (ring vs big cache, sharded
vs unsharded, speculative vs plain — all over the same int8 cache)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tf_operator_tpu.models import llama
from tf_operator_tpu.models.quant import QTensor, quantize_tensor


def _f32(**kw):
    kw.setdefault("dtype", jnp.float32)
    return llama.tiny(**kw)


def _init(cfg, seed=0, batch=2, prompt_len=12):
    model = llama.Llama(cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(seed + 100), (batch, prompt_len), 0,
        cfg.vocab_size)
    params = model.init(jax.random.PRNGKey(seed), prompt,
                        train=False)["params"]
    return model, prompt, params


# -------------------------------------------------------------- unit level
def test_kv_quantize_elementwise_error_bound():
    """Symmetric absmax int8 over head_dim: every element reconstructs
    within half a quantization step of its own (position, head) scale."""
    x = jax.random.normal(jax.random.PRNGKey(0), (2, 5, 3, 16)) * 3.0
    qt = quantize_tensor(x, axes=(3,))
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (2, 5, 3, 1)
    err = np.abs(np.asarray(qt.dequantize(jnp.float32) - x))
    bound = np.asarray(qt.scale) / 2.0 + 1e-7
    assert (err <= bound).all()


def test_init_cache_kv_quant_layout():
    cfg = _f32()
    cache = llama.init_cache(cfg, batch=2, cache_len=32, kv_quant=True)
    assert len(cache) == cfg.n_layers
    k, v = cache[0]
    assert isinstance(k, QTensor) and isinstance(v, QTensor)
    assert k.q.shape == (2, 32, cfg.n_kv_heads, cfg.head_dim)
    assert k.q.dtype == jnp.int8
    assert k.scale.shape == (2, 32, cfg.n_kv_heads, 1)
    # the int8 cache is ~half the bytes of the bf16 one (tiny's D=16
    # inflates the per-head scale overhead to 1/16th; at a real D=128
    # the ratio is ~0.52)
    bf16 = llama.init_cache(cfg, batch=2, cache_len=32,
                            dtype=jnp.bfloat16)
    q_bytes = sum(x.nbytes for x in jax.tree.leaves(cache))
    b_bytes = sum(x.nbytes for x in jax.tree.leaves(bf16))
    assert q_bytes == 0.625 * b_bytes  # (1 + 4/16) / 2


# ---------------------------------------------------------- logits bound
def test_decode_logits_track_full_precision():
    """Per-step decode logits with the int8 cache stay close to the f32
    cache's: tight relative error on the normalized logit vector and
    near-1 cosine — the bound that makes 'approximate' quantitative."""
    cfg = _f32(n_layers=2, max_len=128)
    model, prompt, params = _init(cfg)
    b = prompt.shape[0]

    def step_logits(kv_quant):
        cache = llama.init_cache(cfg, b, 64, kv_quant=kv_quant)
        logits, cache = model.apply({"params": params}, prompt,
                                    cache=cache, cache_pos=0)
        outs = [logits[:, -1]]
        tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        pos = prompt.shape[1]
        for _ in range(8):
            lg, cache = model.apply({"params": params}, tok[:, None],
                                    cache=cache, cache_pos=jnp.int32(pos))
            outs.append(lg[:, 0])
            tok = jnp.argmax(lg[:, 0], -1).astype(jnp.int32)
            pos += 1
        return np.asarray(jnp.stack(outs))

    full = step_logits(False)
    quant = step_logits(True)
    # normalize per distribution: logits are shift-invariant
    f = full - full.mean(-1, keepdims=True)
    g = quant - quant.mean(-1, keepdims=True)
    rel = np.abs(f - g).max() / np.abs(f).max()
    cos = (f * g).sum(-1) / np.maximum(
        np.linalg.norm(f, axis=-1) * np.linalg.norm(g, axis=-1), 1e-9)
    assert rel < 0.08, f"int8-kv logit drift {rel:.3f}"
    assert cos.min() > 0.995, f"cosine {cos.min():.4f}"


# ------------------------------------------------------- exact contracts
def test_ring_cache_equals_big_cache_under_int8kv():
    """Windowed model, int8 ring of O(window) slots vs int8 big cache:
    the written values are identical and the window hides the rest, so
    tokens must be EXACTLY equal (the ring logic is orthogonal to the
    cache representation)."""
    cfg = _f32(sliding_window=16, max_len=256, n_layers=2)
    model, prompt, params = _init(cfg, prompt_len=20)
    want = llama.generate(model, params, prompt, 40, cache_len=128,
                          kv_quant=True)
    got = llama.generate(model, params, prompt, 40, cache_len=32,
                         kv_quant=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_chunked_prefill_equals_one_pass_under_int8kv():
    """Chunked prefill writes the same quantized values as the one-pass
    prefill (per-position scales are order-independent) — exact."""
    cfg = _f32(max_len=128, n_layers=2)
    model, prompt, params = _init(cfg, prompt_len=40)
    want = llama.generate(model, params, prompt, 8, kv_quant=True)
    got = llama.generate(model, params, prompt, 8, kv_quant=True,
                         prefill_chunk=16)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_speculative_greedy_exact_over_int8kv():
    """Speculation over int8 caches: token-identical to plain decode
    over the SAME int8 cache (exactness is relative to the cache
    representation), including the wrapping ring verify write."""
    from tf_operator_tpu.models.speculative import speculative_generate

    cfg = _f32(sliding_window=12, max_len=256, n_layers=2)
    model, prompt, params = _init(cfg, prompt_len=10, batch=1)
    draft, _, dparams = _init(
        _f32(sliding_window=12, max_len=256, n_layers=1), seed=5,
        prompt_len=10, batch=1)
    want = llama.generate(model, params, prompt, 40, kv_quant=True)
    got = speculative_generate(model, params, draft, dparams, prompt,
                               40, k=3, cache_len=16, draft_cache_len=16,
                               kv_quant=True)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_tp_sharded_int8kv_matches_single_device():
    """int8 KV under a tp mesh: the QTensor cache takes the same
    kv-head sharding (scale rides along) — sharding-invariant tokens."""
    from tf_operator_tpu.parallel.mesh import make_mesh
    from tf_operator_tpu.parallel.tp import (
        kv_cache_sharding, transformer_param_sharding,
    )

    cfg = _f32(max_len=64)
    model, prompt, params = _init(cfg, batch=4)
    want = llama.generate(model, params, prompt, 8, kv_quant=True)
    mesh = make_mesh({"tp": 2, "dp": len(jax.devices()) // 2})
    sp = jax.device_put(params, transformer_param_sharding(params, mesh))
    csh = kv_cache_sharding(cfg, mesh, 4)
    got = llama.generate(model, sp, prompt, 8, kv_quant=True,
                         cache_sharding=csh)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_int8kv_composes_with_int8_weights():
    """Both HBM streams int8 at once: weights (params_transform) + KV
    cache — runs end to end and emits in-vocab tokens."""
    from tf_operator_tpu.models import quant

    cfg = _f32(tie_embeddings=True, max_len=128, n_layers=2)
    model, prompt, params = _init(cfg)
    qp = quant.quantize_params(params)
    out = llama.generate(model, qp, prompt, 12, kv_quant=True,
                         params_transform=quant.make_dequantizer(cfg.dtype))
    a = np.asarray(out)
    assert a.shape == (2, 12)
    assert ((a >= 0) & (a < cfg.vocab_size)).all()
