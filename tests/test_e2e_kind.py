"""Live-cluster e2e against a real apiserver (kind or any cluster).

The executable form of the claim "the ClusterClient + operator run
unmodified on a real apiserver" (VERDICT r2 missing #1; reference
analogue: the Argo e2e tier on a real cluster,
test/workflows/components/workflows.libsonnet:216-291).  Unrunnable in
the offline build environment — the `.github/workflows/ci.yaml`
`kind-e2e` job provides the cluster: it builds the operator image, loads
it into kind, applies manifests/overlays/kind-e2e, and runs this
module with E2E_KIND=1.

Locally:  kind create cluster && \
          docker build -t kubeflow/tpu-training-operator:latest \
              -f build/images/tpu-training-operator/Dockerfile . && \
          kind load docker-image kubeflow/tpu-training-operator:latest && \
          kubectl apply -k manifests/overlays/kind-e2e && \
          E2E_KIND=1 python -m pytest tests/test_e2e_kind.py -v
"""
import os
import time
import uuid

import pytest

pytestmark = pytest.mark.skipif(
    os.environ.get("E2E_KIND") != "1" or not os.environ.get("KUBECONFIG"),
    reason="needs a live cluster: set E2E_KIND=1 and KUBECONFIG",
)


@pytest.fixture(scope="module")
def cluster():
    from tf_operator_tpu.k8s.client import ClusterClient

    c = ClusterClient.from_kubeconfig(os.environ["KUBECONFIG"])
    yield c
    c.close()


def _wait(pred, what, timeout=180.0, interval=1.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        last = pred()
        if last:
            return last
        time.sleep(interval)
    raise TimeoutError(f"timeout waiting for {what} (last={last!r})")


def test_simple_tfjob_succeeds_on_real_cluster(cluster):
    """The reference's simple_tfjob_tests.py scenario on a live apiserver:
    create -> pods run with the naming contract -> worker-0 exit 0 ->
    Succeeded -> no creation-failure events -> delete."""
    from tf_operator_tpu.sdk.client import JobClient

    name = f"kind-e2e-{uuid.uuid4().hex[:6]}"
    client = JobClient(cluster, kind="TFJob")
    client.create({
        "apiVersion": "kubeflow.org/v1", "kind": "TFJob",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {"tfReplicaSpecs": {"Worker": {
            "replicas": 1,
            "restartPolicy": "Never",
            "template": {"spec": {
                "restartPolicy": "Never",
                "containers": [{
                    "name": "tensorflow",
                    "image": "python:3.11-slim",
                    "command": ["python", "-c",
                                "import os; print('TF_CONFIG' in os.environ)"],
                }],
            }},
        }}},
    })
    try:
        # pod naming contract {job}-{rt}-{i} (reference
        # pod_names_validation_tests.py)
        _wait(
            lambda: any(
                p["metadata"]["name"] == f"{name}-worker-0"
                for p in cluster.list_pods(
                    namespace="default", selector={"job-name": name})
            ),
            f"pod {name}-worker-0",
        )
        state = _wait(
            lambda: client.get_job_status(name) in ("Succeeded", "Failed")
            and client.get_job_status(name),
            "terminal state",
        )
        assert state == "Succeeded", (
            f"job ended {state}: "
            f"{client.get(name).get('status', {}).get('conditions')}"
        )
        # no creation-failure events (reference tf_job_client.py:363-400)
        warnings = [
            e for e in cluster.list("Event", namespace="default")
            if e.get("type") == "Warning"
            and e.get("involvedObject", {}).get("name", "").startswith(name)
            and "Failed" in e.get("reason", "")
        ]
        assert warnings == [], warnings
    finally:
        client.delete(name)
    _wait(
        lambda: not any(
            j["metadata"]["name"] == name
            for j in cluster.list("TFJob", namespace="default")
        ),
        "job deletion",
        timeout=60.0,
    )
