"""Serving fleet (ISSUE 14): occupancy router, telemetry autoscaler,
TPUServingJob operator integration, seeded chaos.

Late-alphabet file per the tier-1 870s-cap discipline: everything here is
SimClock-driven (no real sleeps); the long fleet soak is marked slow.
"""
import json

import pytest

from tf_operator_tpu.api.servingjob import AutoscaleSpec
from tf_operator_tpu.cmd.manager import OperatorManager
from tf_operator_tpu.cmd.options import ServerOptions, parse_args
from tf_operator_tpu.controllers.registry import EnabledSchemes
from tf_operator_tpu.engine import metrics, servefleet
from tf_operator_tpu.engine.servefleet import (
    DRAIN_ANNOTATION, AutoscalePolicy, FleetAutoscaler,
)
from tf_operator_tpu.k8s.chaos import FaultInjector, SimClock
from tf_operator_tpu.k8s.fake import FakeCluster
from tf_operator_tpu.models.fleetsim import FleetHarness, make_trace
from tf_operator_tpu.models.router import (
    DRAINING, EJECTED, READY, UNHEALTHY, FleetRouter, ServeRequest,
)
from tf_operator_tpu.sdk.cli import Cli, make_parser
from tf_operator_tpu.sdk.cli import run as cli_run


# ---------------------------------------------------------------- helpers
def make_router(policy="occupancy", **kw):
    clock = SimClock()
    kw.setdefault("max_inflight_per_replica", 4)
    kw.setdefault("health_interval", 2.0)
    kw.setdefault("block_size", 16)
    return FleetRouter(policy=policy, clock=clock, **kw), clock


def ready_replica(router, rid, free=100, total=100, queue=0):
    router.add_replica(rid)
    router.observe(rid, free, total, queue)


def req(rid, prompt=16, max_new=16):
    return ServeRequest(rid, prompt, max_new)


def serving_job(name="llm", replicas=2, autoscale=None, image="srv:1",
                shape=None):
    spec = {
        "servingReplicaSpecs": {"Replica": {
            "replicas": replicas,
            "template": {"spec": {"containers": [
                {"name": "serve", "image": image}
            ]}},
        }},
    }
    if shape is not None:
        spec["sliceShape"] = shape
    if autoscale is not None:
        spec["autoscale"] = autoscale
    return {
        "apiVersion": "kubeflow.org/v1", "kind": "TPUServingJob",
        "metadata": {"name": name, "namespace": "default",
                     "uid": f"uid-{name}"},
        "spec": spec,
    }


def make_operator(inj, clock, **opt_kw):
    opts = ServerOptions(
        enabled_schemes=EnabledSchemes(["TPUServingJob"]), **opt_kw
    )
    mgr = OperatorManager(inj, opts, engine_kwargs={"clock": clock})
    mgr.factory.start_all()
    assert mgr.factory.wait_for_cache_sync()
    return mgr


def pump(mgr, inj, n=6, dt=1.0):
    for _ in range(n):
        mgr.process_until_idle()
        inj.step(dt)
    mgr.process_until_idle()


# ------------------------------------------------------------------ router
def test_router_occupancy_picks_most_free_blocks_then_shortest_queue():
    router, _ = make_router()
    ready_replica(router, "r0", free=10, queue=0)
    ready_replica(router, "r1", free=80, queue=3)
    ready_replica(router, "r2", free=80, queue=1)
    # r1/r2 tie on free blocks; r2's shorter queue wins
    assert router.submit(req("a")) == "r2"
    # debits: r2 now carries a's blocks+count, r1 becomes best
    assert router.submit(req("b")) == "r1"


def test_router_tie_breaks_deterministically_by_replica_id():
    router, _ = make_router()
    ready_replica(router, "r1", free=50)
    ready_replica(router, "r0", free=50)
    assert router.submit(req("a")) == "r0"


def test_router_debits_spread_a_burst_between_heartbeats():
    """A burst dispatched inside one heartbeat interval must not convoy
    the replica that merely LOOKED emptiest at the last report."""
    router, _ = make_router()
    ready_replica(router, "r0", free=100)
    ready_replica(router, "r1", free=90)
    picks = [router.submit(req(f"q{i}", prompt=48, max_new=16))
             for i in range(4)]
    assert set(picks) == {"r0", "r1"}  # not all on r0


def test_router_bounded_inflight_parks_overflow_in_queue():
    router, _ = make_router(max_inflight_per_replica=2)
    ready_replica(router, "r0")
    assert router.submit(req("a")) == "r0"
    assert router.submit(req("b")) == "r0"
    assert router.submit(req("c")) is None  # bound hit: parked
    assert router.queue_depth() == 1
    # a completion frees the bound and pumps the queue
    router.finish("r0", "a")
    assert router.queue_depth() == 0
    assert router.inflight("r0") == 2  # b + c


def test_router_occupancy_respects_block_cost():
    router, _ = make_router()
    ready_replica(router, "r0", free=2, total=100)
    # 1 block fits, 4 blocks do not (cost = ceil((prompt+new)/16))
    assert router.submit(req("small", prompt=8, max_new=8)) == "r0"
    assert router.submit(req("big", prompt=32, max_new=32)) is None


def test_router_round_robin_cycles_blindly():
    router, _ = make_router(policy="round_robin")
    for rid in ("r0", "r1", "r2"):
        ready_replica(router, rid)
    picks = [router.submit(req(f"q{i}")) for i in range(6)]
    assert picks == ["r0", "r1", "r2", "r0", "r1", "r2"]


def test_router_drain_blocks_dispatch_and_scale_in_waits_for_empty():
    router, _ = make_router()
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    router.submit(req("a"))  # lands on r0 (tie-break)
    assert router.drain("r0") == 1
    assert router.replica_state("r0") == DRAINING
    # all new traffic avoids the draining replica
    assert router.submit(req("b")) == "r1"
    router.finish("r0", "a")
    assert router.inflight("r0") == 0
    # clean removal after drain requeues nothing
    assert router.remove_replica("r0", requeue=False) == 0


def test_router_health_expiry_redispatches_exactly_once():
    router, clock = make_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    assert router.submit(req("a")) == "r0"
    # r1 keeps heartbeating; r0 goes silent past the health interval
    clock.advance(2.5)
    router.observe("r1", 100, 100, 0)
    assert router.tick() == ["r0"]
    assert router.replica_state("r0") == UNHEALTHY
    # a moved to r1, exactly once
    assert router.redispatches == {"a": 1}
    assert router.inflight("r1") == 2 - 1  # a (b not submitted yet)
    # nothing dispatches to the unhealthy replica
    assert router.submit(req("b")) == "r1"
    # a second sweep re-dispatches nothing (ledger already moved)
    assert router.tick() == []
    assert router.redispatches == {"a": 1}


def test_router_duplicate_completion_delivers_once():
    """A false-positive expiry (slow replica, not dead) may generate
    twice but must deliver once: the first completion wins."""
    router, clock = make_router(health_interval=1.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    assert router.submit(req("a")) == "r0"
    clock.advance(1.5)
    router.observe("r1", 100, 100, 0)
    router.tick()  # a re-dispatched to r1
    # r1 finishes first -> delivered; the recovered r0 finishes later ->
    # dropped as a duplicate
    assert router.finish("r1", "a") is True
    router.observe("r0", 100, 100, 0)  # r0 was merely slow; it recovers
    assert router.replica_state("r0") == READY
    assert router.finish("r0", "a") is False


def test_router_rejects_unknown_policy():
    with pytest.raises(ValueError):
        FleetRouter(policy="wishful")


def test_router_drain_fence_survives_unhealthy_detour():
    """A draining replica that misses heartbeats and then recovers must
    come back DRAINING, never READY — the autoscaler is about to delete
    it, and resuming dispatch would hand it doomed requests."""
    router, clock = make_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    router.drain("r0")
    clock.advance(2.5)
    router.observe("r1", 100, 100, 0)
    assert router.tick() == ["r0"]
    # the late heartbeat revives it — into the drain fence, not dispatch
    router.observe("r0", 100, 100, 0)
    assert router.replica_state("r0") == DRAINING
    assert router.submit(req("a")) == "r1"
    # sync_drains with the victim no longer named releases the fence
    router.sync_drains([])
    assert router.replica_state("r0") == READY


def test_router_sync_drains_applies_annotation_targets():
    """The read side of the kubeflow.org/fleet-drain channel: a
    front-end router applies drain_targets(job) on CR watch events."""
    from tf_operator_tpu.engine.servefleet import drain_targets

    router, _ = make_router()
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    job = {"metadata": {"annotations": {
        DRAIN_ANNOTATION: json.dumps(["r1"])}}}
    router.sync_drains(drain_targets(job))
    assert router.replica_state("r1") == DRAINING
    assert router.submit(req("a")) == "r0"
    # annotation cleared (drain done/abandoned) -> released
    router.sync_drains(drain_targets({"metadata": {}}))
    assert router.replica_state("r1") == READY
    # malformed annotation reads as empty, never raises
    assert drain_targets({"metadata": {"annotations": {
        DRAIN_ANNOTATION: "{not json"}}}) == []


def test_router_rejects_request_bigger_than_every_pool():
    """A request whose worst case exceeds every replica's WHOLE pool can
    never dispatch: it is refused upfront (serve_loop's own validation,
    restated at the fleet boundary) instead of wedging the FIFO head
    and starving everything queued behind it."""
    router, _ = make_router()
    ready_replica(router, "r0", free=100, total=100)
    monster = req("huge", prompt=3200, max_new=100)  # > 100 blocks
    assert router.submit(monster) is None
    assert router.rejected == ["huge"]
    assert router.queue_depth() == 0  # refused, not parked
    # normal traffic flows — nothing is starved behind the reject
    assert router.submit(req("a")) == "r0"
    # a merely-temporarily-unfittable request still queues (FIFO hold is
    # the replica memory-gate semantics; the autoscaler clears it)
    router.observe("r0", 1, 100, 0)
    assert router.submit(req("b", prompt=64, max_new=64)) is None
    assert router.queue_depth() == 1


def test_router_pump_evicts_oversized_head_queued_before_heartbeats():
    """An oversized request that slips past submit (no snapshots yet)
    must be evicted at pump time, not wedge the FIFO head forever."""
    router, _ = make_router()
    router.add_replica("r0")  # STARTING: no snapshot, no capacity known
    monster = req("huge", prompt=3200, max_new=100)
    assert router.submit(monster) is None      # queued (cap unknown)
    assert router.submit(req("a")) is None     # queued behind it
    assert router.queue_depth() == 2
    # first heartbeat: the head is now provably unfittable — evicted,
    # and the dispatchable request behind it flows
    router.observe("r0", 100, 100, 0)
    assert router.rejected == ["huge"]
    assert router.queue_depth() == 0
    assert router.inflight("r0") == 1


def test_router_mark_ready_without_heartbeat_still_expires():
    """mark_ready (the external STARTING->READY signal) must not create
    an unexpirable replica: with no heartbeat ever, the add/ready time
    anchors the health sweep."""
    router, clock = make_router(policy="round_robin", health_interval=2.0)
    router.add_replica("r0")
    router.add_replica("r1")
    router.mark_ready("r0")
    router.observe("r1", 100, 100, 0)
    assert router.submit(req("a")) == "r0"  # blind rr dispatches to it
    clock.advance(2.5)
    router.observe("r1", 100, 100, 0)
    assert router.tick() == ["r0"]  # silence expired it
    assert router.redispatches == {"a": 1}
    # and mark_dead requeues on the external death signal, exactly once
    ready_replica(router, "r2")
    holder = router.submit(req("b"))
    assert holder in ("r1", "r2")
    assert router.mark_dead(holder) >= 1  # b (and possibly a) moved
    assert router.redispatches.get("b") == 1


def test_router_ledgers_are_bounded():
    router, _ = make_router()
    ready_replica(router, "r0")
    router._completed.cap = 8
    for i in range(32):
        rid = f"q{i}"
        router.submit(ServeRequest(rid, 8, 8))
        router.finish("r0", rid)
    assert len(router._completed) <= 8
    assert len(router._completed._order) <= 8


def test_router_duplicate_completion_still_pumps_queue():
    """A duplicate completion frees the tracked dispatch slot on the
    slow replica — the queue must drain into it immediately, not wait
    for the next event."""
    router, clock = make_router(max_inflight_per_replica=1,
                                health_interval=1.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    assert router.submit(req("a")) == "r0"
    # r0 goes quiet: a re-dispatches to r1 (fills r1's bound)
    clock.advance(1.5)
    router.observe("r1", 100, 100, 0)
    assert router.tick() == ["r0"]
    assert router.inflight("r1") == 1
    # r0 was merely slow: it recovers (empty ledger) and takes b; c has
    # nowhere to go
    router.observe("r0", 100, 100, 0)
    assert router.submit(req("b")) == "r0"
    assert router.submit(req("c")) is None
    # r0 delivers the ORIGINAL a first (first completion wins)...
    assert router.finish("r0", "a") is True
    assert router.queue_depth() == 1  # both bounds still full (b on r0, a on r1)
    # ...then r1's duplicate lands: dropped, but its freed slot must
    # still pump c out of the queue
    assert router.finish("r1", "a") is False
    assert router.queue_depth() == 0
    assert router.inflight("r1") == 1  # c dispatched onto r1


# -------------------------------------------------------- autoscale policy
def auto_spec(**kw):
    kw.setdefault("min_replicas", 2)
    kw.setdefault("max_replicas", 6)
    kw.setdefault("scale_out_queue_wait_p99_s", 2.0)
    kw.setdefault("scale_out_blocked_admissions", 4)
    kw.setdefault("scale_in_occupancy_floor", 0.3)
    return AutoscaleSpec(**kw)


def test_policy_scales_out_on_queue_wait_p99():
    policy = AutoscalePolicy(auto_spec())
    d = policy.decide(0.0, 2, queue_wait_p99_s=3.0, blocked_delta=0,
                      occupancy=0.5)
    assert d.direction == "out"
    assert d.trigger == "serving_queue_wait_seconds_p99"


def test_policy_scales_out_on_blocked_admissions():
    policy = AutoscalePolicy(auto_spec())
    d = policy.decide(0.0, 2, queue_wait_p99_s=0.1, blocked_delta=5,
                      occupancy=0.9)
    assert d.direction == "out"
    assert d.trigger == "serving_admission_blocked_on_memory_total"


def test_policy_scales_in_under_occupancy_floor_without_pressure():
    policy = AutoscalePolicy(auto_spec())
    d = policy.decide(0.0, 4, queue_wait_p99_s=0.1, blocked_delta=0,
                      occupancy=0.1)
    assert d.direction == "in"
    # queue pressure vetoes scale-in even under the floor
    d = policy.decide(0.0, 4, queue_wait_p99_s=1.5, blocked_delta=0,
                      occupancy=0.1)
    assert d.direction is None


def test_policy_unknown_occupancy_vetoes_scale_in():
    """occupancy None = no replica has reported block telemetry: unknown
    is not idle — a fleet with a dead scrape loop must not be drained to
    minReplicas on zero evidence."""
    policy = AutoscalePolicy(auto_spec())
    assert policy.decide(0.0, 4, 0.0, 0, None).direction is None
    # scale-out triggers still work without block telemetry
    assert policy.decide(0.0, 4, 5.0, 0, None).direction == "out"


def test_policy_respects_bounds_and_cooldowns():
    policy = AutoscalePolicy(auto_spec(), out_cooldown_s=1.0,
                             in_cooldown_s=10.0)
    # at max: no out; at min: no in
    assert policy.decide(0.0, 6, 5.0, 9, 0.9).direction is None
    assert policy.decide(0.0, 2, 0.0, 0, 0.0).direction is None
    # out cooldown is short, in cooldown long
    policy.acted(0.0, "out")
    assert policy.decide(0.5, 3, 5.0, 0, 0.5).direction is None
    assert policy.decide(1.5, 3, 5.0, 0, 0.5).direction == "out"
    policy.acted(2.0, "in")
    assert policy.decide(8.0, 4, 0.0, 0, 0.1).direction is None
    assert policy.decide(12.5, 4, 0.0, 0, 0.1).direction == "in"


# -------------------------------------------------------------- validation
def test_servingjob_validation_rejects_bad_autoscale():
    from tf_operator_tpu.api import job as jobapi
    from tf_operator_tpu.api import servingjob as api
    from tf_operator_tpu.controllers.serving import ServingAdapter

    adapter = ServingAdapter()
    good = adapter.from_dict(serving_job(autoscale={
        "minReplicas": 1, "maxReplicas": 4}))
    adapter.set_defaults(good)
    adapter.validate(good)
    for bad_auto in (
        {"minReplicas": 0},
        {"minReplicas": 4, "maxReplicas": 2},
        {"maxInflightPerReplica": 0},
        {"scaleOutQueueWaitP99S": 0},
        {"scaleInOccupancyFloor": 1.5},
        {"scaleOutBlockedAdmissions": 0},
    ):
        job = adapter.from_dict(serving_job(autoscale=bad_auto))
        adapter.set_defaults(job)
        with pytest.raises(jobapi.ValidationError):
            adapter.validate(job)
    bad_shape = adapter.from_dict(serving_job(shape="gpu-8x"))
    adapter.set_defaults(bad_shape)
    with pytest.raises(jobapi.ValidationError):
        adapter.validate(bad_shape)
    # defaults stamp the slice-shape annotation for the warm pool
    assert (
        good.replica_specs["Replica"].template["metadata"]["annotations"][
            api.SHAPE_ANNOTATION
        ] == api.DEFAULT_SLICE_SHAPE
    )


# -------------------------------------------------- operator integration
def test_operator_reconciles_fleet_with_identity_env():
    clock = SimClock()
    inj = FaultInjector(FakeCluster(), seed=7, clock=clock)
    mgr = make_operator(inj, clock)
    inj.create("TPUServingJob", serving_job(replicas=3, shape="v5e-8"))
    pump(mgr, inj)
    pods = sorted(inj.list_pods(), key=lambda p: p["metadata"]["name"])
    assert [p["metadata"]["name"] for p in pods] == [
        "llm-replica-0", "llm-replica-1", "llm-replica-2"
    ]
    cur = inj.get("TPUServingJob", "default", "llm")
    conds = {c["type"]: c["status"] for c in cur["status"]["conditions"]}
    assert conds.get("Running") == "True"
    assert "Scheduling" not in conds
    env = {e["name"]: e["value"]
           for e in pods[1]["spec"]["containers"][0]["env"]}
    assert env["SERVING_REPLICA_ID"] == "llm-replica-1"
    assert env["SERVING_FLEET_SIZE"] == "3"
    assert env["TPU_SLICE_SHAPE"] == "v5e-8"
    assert (
        pods[0]["metadata"]["annotations"]["kubeflow.org/slice-shape"]
        == "v5e-8"
    )
    mgr.stop()


def test_fleet_bypasses_cluster_scheduler_gang_admission():
    """Gang-free: a fleet whose aggregate chip demand could NEVER gang-fit
    the inventory still gets every pod (replicas admit independently,
    i.e. not at all — the scheduler seam is bypassed)."""
    clock = SimClock()
    inj = FaultInjector(FakeCluster(), seed=7, clock=clock)
    mgr = make_operator(
        inj, clock, scheduler_enabled=True, scheduler_nodes=["n0=v5e-8"],
    )
    # 3 x v5e-8 = 24 chips > the 8-chip inventory: a gang would park
    inj.create("TPUServingJob", serving_job(replicas=3, shape="v5e-8"))
    pump(mgr, inj)
    assert len(inj.list_pods()) == 3
    cur = inj.get("TPUServingJob", "default", "llm")
    conds = {c["type"]: c["status"] for c in cur["status"]["conditions"]}
    assert conds.get("Running") == "True"
    assert "Scheduling" not in conds
    mgr.stop()


def test_fleet_resize_never_enters_elastic_phase_machine():
    from tf_operator_tpu.engine.controller import RESIZE_STATE_ANNOTATION

    clock = SimClock()
    inj = FaultInjector(FakeCluster(), seed=7, clock=clock)
    mgr = make_operator(inj, clock, elastic_resize=True)
    inj.create("TPUServingJob", serving_job(replicas=3))
    pump(mgr, inj)
    assert len(inj.list_pods()) == 3
    cur = inj.get("TPUServingJob", "default", "llm")
    cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] = 2
    inj.update("TPUServingJob", cur)
    pump(mgr, inj)
    cur = inj.get("TPUServingJob", "default", "llm")
    names = sorted(p["metadata"]["name"] for p in inj.list_pods())
    assert names == ["llm-replica-0", "llm-replica-1"]
    conds = {c["type"] for c in cur["status"]["conditions"]}
    assert "Resizing" not in conds
    ann = (cur["metadata"].get("annotations") or {})
    assert RESIZE_STATE_ANNOTATION not in ann
    mgr.stop()


def test_fleet_replica_kill_restart_counters_exact_and_log_byte_identical():
    """The operator half of the chaos satellite: a killed serving replica
    restarts with exact counters, and the seeded log replays
    byte-identically."""
    def scenario(seed):
        clock = SimClock()
        inj = FaultInjector(FakeCluster(), seed=seed, clock=clock)
        mgr = make_operator(inj, clock)
        inj.create("TPUServingJob", serving_job(replicas=3))
        pump(mgr, inj, n=4)
        inj.at(6.0, lambda: inj.kill_pod("default", "llm-replica-1"),
               "chaos kill llm-replica-1")
        pump(mgr, inj, n=10)
        cur = inj.get("TPUServingJob", "default", "llm")
        rs = cur["status"]["replicaStatuses"]["Replica"]
        mgr.stop()
        return list(inj.log), rs, dict(inj.retryable_kills)

    log1, rs1, kills1 = scenario(1337)
    log2, rs2, kills2 = scenario(1337)
    assert log1 == log2
    assert rs1 == rs2
    assert kills1 == {("default/llm", "replica"): 1}
    assert rs1["restarts"] == 1
    assert rs1["active"] == 3  # replaced, fleet whole again


def test_scale_out_claims_warm_pool_standby():
    clock = SimClock()
    inj = FaultInjector(FakeCluster(), seed=7, clock=clock)
    mgr = make_operator(inj, clock, warm_pool_size=2)
    base_claims = metrics.WARM_POOL_CLAIMS.get({"shape": "v5e-1"})
    mgr.warm_pool.replenish()
    inj.step(2.0)  # kubelet marks standbys Running
    assert mgr.warm_pool.ready_count("v5e-1") == 2
    # the pool's image so the strict-image claim matches
    inj.create(
        "TPUServingJob", serving_job(replicas=1, image="warm-runtime")
    )
    pump(mgr, inj)
    assert metrics.WARM_POOL_CLAIMS.get({"shape": "v5e-1"}) == base_claims + 1
    cur = inj.get("TPUServingJob", "default", "llm")
    assert cur["status"]["replicaStatuses"]["Replica"]["active"] == 1
    # the claimed pod is a standby wearing the member identity annotation
    claimed = [
        p for p in inj.list_pods()
        if (p["metadata"].get("annotations") or {}).get(
            "kubeflow.org/warm-bound-name") == "llm-replica-0"
    ]
    assert len(claimed) == 1
    mgr.stop()


# ------------------------------------------------------- fleet autoscaler
def autoscaled_operator(seed=7, recorder=None):
    clock = SimClock()
    inj = FaultInjector(FakeCluster(), seed=seed, clock=clock)
    mgr = make_operator(inj, clock, timeline_events_per_job=64)
    asc = FleetAutoscaler(
        inj, interval=1.0, clock=clock,
        recorder=recorder if recorder is not None else mgr.recorder,
    )
    inj.create("TPUServingJob", serving_job(replicas=2, autoscale={
        "minReplicas": 1, "maxReplicas": 4,
        "scaleOutQueueWaitP99S": 1.0,
        "scaleOutBlockedAdmissions": 3,
        "scaleInOccupancyFloor": 0.3,
    }))
    pump(mgr, inj, n=4)
    return clock, inj, mgr, asc


def test_autoscaler_scale_out_patch_and_timeline_decision():
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    asc.report("default/llm", "llm-replica-0", free_blocks=5,
               total_blocks=100, queue_depth=6, inflight=8,
               queue_waits=[2.0, 2.5])
    asc.tick()
    pump(mgr, inj)
    cur = inj.get("TPUServingJob", "default", "llm")
    assert cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] == 3
    assert len(inj.list_pods()) == 3
    tl = mgr.recorder.timeline("default/llm")
    records = [e for e in tl["events"] if e["source"] == "servefleet"]
    assert [e["event"] for e in records] == ["scale_out"]
    detail = records[0]["detail"]
    assert detail["trigger"] == "serving_queue_wait_seconds_p99"
    assert detail["value"] == 2.5
    assert detail["threshold"] == 1.0
    mgr.stop()


def test_autoscaler_scale_in_two_phase_drain_then_delete():
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    clock.advance(40.0)
    for rid in ("llm-replica-0", "llm-replica-1"):
        asc.report("default/llm", rid, free_blocks=95, total_blocks=100,
                   queue_depth=0,
                   inflight=(2 if rid == "llm-replica-1" else 0))
    asc.tick()
    cur = inj.get("TPUServingJob", "default", "llm")
    # phase 1: victim named in the drain annotation, count untouched
    assert json.loads(
        cur["metadata"]["annotations"][DRAIN_ANNOTATION]
    ) == ["llm-replica-1"]
    assert cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] == 2
    # victim still busy: another tick must not delete it
    asc.tick()
    assert len(inj.list_pods()) == 2
    # drained: the -1 patch lands and the engine removes the pod
    asc.report("default/llm", "llm-replica-1", free_blocks=100,
               total_blocks=100, queue_depth=0, inflight=0)
    asc.tick()
    pump(mgr, inj)
    cur = inj.get("TPUServingJob", "default", "llm")
    assert cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] == 1
    assert (cur["metadata"].get("annotations") or {}).get(
        DRAIN_ANNOTATION) is None
    assert [p["metadata"]["name"] for p in inj.list_pods()] == [
        "llm-replica-0"
    ]
    tl = mgr.recorder.timeline("default/llm")
    events = [e["event"] for e in tl["events"]
              if e["source"] == "servefleet"]
    assert events == ["scale_in", "replica_drained"]
    mgr.stop()


def test_autoscaler_drain_timeout_unwedges_a_dead_victim():
    """A victim that dies permanently mid-drain (never reports again)
    must not wedge the job's autoscaling forever: past drain_timeout_s
    the drain completes on the evidence available."""
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    asc.drain_timeout_s = 5.0
    clock.advance(40.0)
    for rid in ("llm-replica-0", "llm-replica-1"):
        asc.report("default/llm", rid, free_blocks=95, total_blocks=100,
                   queue_depth=0,
                   inflight=(2 if rid == "llm-replica-1" else 0))
    asc.tick()  # phase 1: drain llm-replica-1
    cur = inj.get("TPUServingJob", "default", "llm")
    assert DRAIN_ANNOTATION in cur["metadata"]["annotations"]
    # the victim dies and never reports again; its last report said
    # inflight=2 — without the timeout this would park forever
    clock.advance(3.0)
    asc.tick()
    assert inj.get("TPUServingJob", "default", "llm")["spec"][
        "servingReplicaSpecs"]["Replica"]["replicas"] == 2
    clock.advance(4.0)  # past drain_timeout_s
    asc.tick()
    pump(mgr, inj)
    cur = inj.get("TPUServingJob", "default", "llm")
    assert cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] == 1
    tl = mgr.recorder.timeline("default/llm")
    drained = [e for e in tl["events"]
               if e["source"] == "servefleet"
               and e["event"] == "replica_drained"]
    assert drained and drained[0]["detail"].get("timed_out") is True
    mgr.stop()


def test_autoscaler_releases_drain_when_autoscale_removed():
    """Deleting the autoscale block mid-drain must RELEASE the victim
    (annotation cleared, draining state dropped), not park it fenced
    off dispatch forever."""
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    clock.advance(40.0)
    for rid in ("llm-replica-0", "llm-replica-1"):
        asc.report("default/llm", rid, free_blocks=95, total_blocks=100,
                   queue_depth=0,
                   inflight=(2 if rid == "llm-replica-1" else 0))
    asc.tick()  # phase 1: drain begins
    cur = inj.get("TPUServingJob", "default", "llm")
    assert DRAIN_ANNOTATION in cur["metadata"]["annotations"]
    del cur["spec"]["autoscale"]
    inj.update("TPUServingJob", cur)
    asc.tick()
    cur = inj.get("TPUServingJob", "default", "llm")
    assert DRAIN_ANNOTATION not in (cur["metadata"].get("annotations") or {})
    assert cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] == 2
    assert asc._draining == {}
    mgr.stop()


def test_autoscaler_clamped_scale_in_records_nothing():
    """minReplicas raised mid-drain clamps the patch to a no-op: the
    victim is released and NO replica_drained / dir=in event is
    recorded — observability must not report a scale-in that never
    happened."""
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    base_in = metrics.SERVING_FLEET_SCALE_EVENTS.get({"dir": "in"})
    clock.advance(40.0)
    for rid in ("llm-replica-0", "llm-replica-1"):
        asc.report("default/llm", rid, free_blocks=95, total_blocks=100,
                   queue_depth=0, inflight=0)
    asc.tick()  # phase 1 (victim idle, but phase 2 runs next tick)
    cur = inj.get("TPUServingJob", "default", "llm")
    assert DRAIN_ANNOTATION in cur["metadata"]["annotations"]
    cur["spec"]["autoscale"]["minReplicas"] = 2  # clamp the pending -1
    inj.update("TPUServingJob", cur)
    asc.tick()
    cur = inj.get("TPUServingJob", "default", "llm")
    assert cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] == 2
    assert DRAIN_ANNOTATION not in (cur["metadata"].get("annotations") or {})
    assert metrics.SERVING_FLEET_SCALE_EVENTS.get({"dir": "in"}) == base_in
    tl = mgr.recorder.timeline("default/llm")
    assert not [e for e in tl["events"]
                if e["source"] == "servefleet"
                and e["event"] == "replica_drained"]
    mgr.stop()


def test_autoscaler_min_raised_above_count_mid_drain_never_scales_up():
    """minReplicas raised ABOVE the current count mid-drain: the drain
    is abandoned at the UNCHANGED count — the drain-completion path must
    never patch the fleet up while recording a scale-in."""
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    base_in = metrics.SERVING_FLEET_SCALE_EVENTS.get({"dir": "in"})
    clock.advance(40.0)
    for rid in ("llm-replica-0", "llm-replica-1"):
        asc.report("default/llm", rid, free_blocks=95, total_blocks=100,
                   queue_depth=0, inflight=0)
    asc.tick()  # phase 1
    cur = inj.get("TPUServingJob", "default", "llm")
    assert DRAIN_ANNOTATION in cur["metadata"]["annotations"]
    cur["spec"]["autoscale"]["minReplicas"] = 4  # above current count 2
    cur["spec"]["autoscale"]["maxReplicas"] = 6
    inj.update("TPUServingJob", cur)
    asc.tick()
    cur = inj.get("TPUServingJob", "default", "llm")
    assert cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] == 2
    assert DRAIN_ANNOTATION not in (cur["metadata"].get("annotations") or {})
    assert metrics.SERVING_FLEET_SCALE_EVENTS.get({"dir": "in"}) == base_in
    mgr.stop()


def test_autoscaler_clears_annotation_when_replicas_field_vanishes():
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    clock.advance(40.0)
    for rid in ("llm-replica-0", "llm-replica-1"):
        asc.report("default/llm", rid, free_blocks=95, total_blocks=100,
                   queue_depth=0, inflight=1)
    asc.tick()  # phase 1: drain begins
    cur = inj.get("TPUServingJob", "default", "llm")
    assert DRAIN_ANNOTATION in cur["metadata"]["annotations"]
    # the count disappears mid-drain: nothing will ever finish the
    # scale-in, so the fence must come off
    del cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"]
    inj.update("TPUServingJob", cur)
    asc.tick()
    cur = inj.get("TPUServingJob", "default", "llm")
    assert DRAIN_ANNOTATION not in (cur["metadata"].get("annotations") or {})
    assert asc._draining == {}
    mgr.stop()


def test_autoscaler_no_telemetry_never_scales_in():
    """--serving-autoscale with no scrape wired (or before the first
    report): the fleet must hold, not drain to minReplicas."""
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    for _ in range(30):
        clock.advance(5.0)
        asc.tick()
    cur = inj.get("TPUServingJob", "default", "llm")
    assert cur["spec"]["servingReplicaSpecs"]["Replica"]["replicas"] == 2
    assert DRAIN_ANNOTATION not in (cur["metadata"].get("annotations") or {})
    mgr.stop()


def test_autoscaler_forgets_deleted_jobs():
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    asc.report("default/llm", "llm-replica-0", free_blocks=50,
               total_blocks=100, queue_depth=0, inflight=0)
    asc.tick()
    assert servefleet.fleet_status("default/llm") is not None
    assert asc._telemetry.get("default/llm")
    inj.delete("TPUServingJob", "default", "llm")
    asc.tick()
    assert servefleet.fleet_status("default/llm") is None
    assert "default/llm" not in asc._telemetry
    mgr.stop()


def test_fleet_metrics_families_exposed():
    router, _ = make_router()
    ready_replica(router, "r0")
    router.submit(req("a"))
    metrics.SERVING_FLEET_SCALE_EVENTS.inc({"dir": "out"})
    text = metrics.expose_all()
    for family in (
        "tpu_operator_serving_fleet_replicas",
        "tpu_operator_serving_router_dispatch_total",
        "tpu_operator_serving_router_queue_depth",
        "tpu_operator_serving_fleet_scale_events_total",
    ):
        assert f"# TYPE {family}" in text


# ----------------------------------------------------------------- CLI
def test_cli_describe_shows_fleet_section(capsys):
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    asc.report("default/llm", "llm-replica-0", free_blocks=40,
               total_blocks=100, queue_depth=2, inflight=3)
    asc.report("default/llm", "llm-replica-1", free_blocks=90,
               total_blocks=100, queue_depth=0, inflight=1,
               queue_waits=[2.0])
    asc.tick()  # publishes status (+ a scale-out: p99 2.0 > 1.0)
    cli = Cli(inj, recorder=mgr.recorder)
    assert cli.describe("TPUServingJob", "llm", "default") == 0
    out = capsys.readouterr().out
    assert "Fleet:" in out
    assert "replica(s) ready" in out
    assert "llm-replica-0: blocks=60/100 (60%) queue=2 inflight=3" in out
    assert "last-scale: dir=out" in out
    mgr.stop()


def test_cli_resize_fleet_is_plain_and_watches_active(capsys):
    clock, inj, mgr, asc = autoscaled_operator()
    cli = Cli(inj)
    args = make_parser().parse_args(
        ["resize", "tpuservingjob", "llm", "4", "--timeout", "0"]
    )
    assert cli_run(args, cli) == 0
    out = capsys.readouterr().out
    assert "fleet resize requested (Replica=2->4" in out
    assert "no drain phase machine" in out
    pump(mgr, inj)
    cur = inj.get("TPUServingJob", "default", "llm")
    assert len(inj.list_pods()) == 4
    conds = {c["type"] for c in cur["status"]["conditions"]}
    assert "Resizing" not in conds
    # with the fleet already converged, a watch returns immediately
    args = make_parser().parse_args(
        ["resize", "tpuservingjob", "llm", "4"]
    )
    assert cli_run(args, cli) == 0
    assert "already at Replica=4" in capsys.readouterr().out
    mgr.stop()


def test_router_gap_recovery_requeues_stalled_books():
    """Degraded mode never expires a lone replica — so when it dies
    AND restarts (fresh process, fresh heartbeat), its pre-outage
    in-flight books would otherwise consume dispatch slots forever.
    A sample landing after a full missed-heartbeat gap requeues the
    progress-stalled entries; a stream that kept progressing through
    a mere telemetry outage stays put."""
    router, clock = make_router(health_interval=2.0)
    ready_replica(router, "r0")
    assert router.submit(req("a")) == "r0"
    assert router.submit(req("b")) == "r0"
    router.note_progress("r0", "b")  # b's stream is alive pre-gap
    clock.advance(3.0)
    assert router.tick() == []  # lone replica: degraded, not expired
    assert router.degraded
    # ...the pod restarted behind the gap and heartbeats fresh, but b
    # kept streaming through what was only a TELEMETRY outage
    router.note_progress("r0", "b")
    router.observe("r0", 100, 100, 0)
    assert not router.degraded
    # a (no progress since dispatch) was re-dispatched; b stayed put
    assert router.redispatches == {"a": 1}
    assert set(router._replicas["r0"].inflight) == {"a", "b"}
    # a's re-dispatch is fresh — it will not instantly re-hedge/expire
    assert router._replicas["r0"].dispatched_at["a"] == clock()


def test_router_lone_replica_dispatch_failure_queues_not_loops():
    """A dispatch failure on the fleet's ONLY replica queues the
    request — re-placing it onto the replica that just refused it
    would turn a dead lone replica into an unbounded
    dispatch→fail→re-place hot loop (degraded mode keeps it READY and
    ejection has no witness).  pump() retries once a sibling exists."""
    router, clock = make_router()
    ready_replica(router, "r0")
    assert router.submit(req("a")) == "r0"
    router.dispatch_failed("r0", "a")
    assert router.inflight("r0") == 0
    assert router.queue_depth() == 1  # parked, not hot-looped
    events_before = len(router.events)
    router.tick()
    assert router.queue_depth() == 1  # no churn while nothing changed
    assert router.inflight("r0") == 0
    # fresh capacity/evidence appears: the parked request dispatches
    ready_replica(router, "r1")
    assert router.queue_depth() == 0
    assert router.inflight("r0") + router.inflight("r1") == 1
    assert len(router.events) > events_before


def test_fleet_frozen_drain_victim_times_out_and_requeues():
    """A FROZEN scale-in victim (accepts dispatch, never completes,
    keeps heartbeating) can never reach inflight==0: the harness's
    drain wait must time out like the operator's — complete the
    scale-in, requeue the trapped requests exactly once — instead of
    silently disabling autoscaling for the rest of the run."""
    harness = FleetHarness(
        "occupancy", n_replicas=3,
        autoscale=auto_spec(min_replicas=2, max_replicas=6,
                            scale_in_occupancy_floor=0.2),
    )
    clock = harness.clock
    victim = "r2"  # highest index: the scale-in pick
    # plant a request directly on the victim (the occupancy tie-break
    # would route a submit elsewhere)
    harness.arrival_t["trapped"] = clock()
    harness.router._dispatch(req("trapped"), victim)
    assert harness.router.inflight(victim) == 1
    harness.freeze(victim)
    harness.router.drain(victim)
    harness._draining = victim
    harness._drain_started = clock()
    clock.advance(harness.drain_timeout_s + 1.0)
    harness._autoscale_tick(clock())
    assert harness._draining is None  # wedge broken
    assert victim not in harness.replicas
    # the trapped request moved to a live sibling exactly once
    assert harness.router.redispatches == {"trapped": 1}
    assert any("scale_in_done replica=r2 timeout=1" in l
               for l in harness.log)


# ------------------------------------------------------------ chaos (sim)
def chaos_fleet_run(seed, kill_at=65.0, victim="r1"):
    trace = make_trace(seed, n_users=300)
    harness = FleetHarness(
        "occupancy", n_replicas=3,
        autoscale=auto_spec(min_replicas=2, max_replicas=6,
                            scale_out_queue_wait_p99_s=1.5,
                            scale_in_occupancy_floor=0.2),
        warm_standbys=4,
    )
    harness.kill(kill_at, victim)
    summary = harness.run(trace, horizon_s=600.0)
    return harness, summary


def test_fleet_kill_chaos_exactly_once_and_byte_identical_per_seed():
    """The chaos satellite: kill a serving replica mid-stream — the
    router stops dispatching within one health interval, its requests
    re-dispatch to siblings exactly once, nothing is lost or duplicated,
    and the whole event log is byte-identical per seed."""
    h1, s1 = chaos_fleet_run(4242)
    h2, s2 = chaos_fleet_run(4242)
    assert h1.log == h2.log
    assert s1 == s2
    # a different seed is a different story (the log is seed-driven)
    h3, _ = chaos_fleet_run(90210)
    assert h3.log != h1.log
    # no loss, no duplicate generation delivered
    assert s1["dropped"] == 0
    assert s1["duplicates"] == 0
    # the victim's orphans re-dispatched exactly once each
    assert s1["redispatches"], "kill landed mid-stream but moved nothing"
    assert all(n == 1 for n in s1["redispatches"].values())
    # dispatch to the dead replica stopped within one health interval
    # (+ one heartbeat of detection slack)
    kill_t = next(
        float(l.split("t=")[1].split()[0]) for l in h1.log
        if l.endswith("kill replica=r1")
    )
    unhealthy_t = next(
        float(l.split("t=")[1].split()[0]) for l in h1.log
        if "replica_unhealthy replica=r1" in l
    )
    assert unhealthy_t - kill_t <= (
        h1.router.health_interval + h1.heartbeat_s + 3 * h1.dt
    )
    last_dispatch_t = max(
        (float(l.split("t=")[1].split()[0]) for l in h1.log
         if "dispatch" in l and l.endswith("replica=r1")),
        default=0.0,
    )
    assert last_dispatch_t <= unhealthy_t


@pytest.mark.slow
def test_fleet_soak_full_trace_with_kills_and_autoscale():
    """Slow soak: the full 1.2k-user bench trace with two mid-burst
    kills — every request still completes exactly once, reactions stay
    within one claim latency, and the log replays byte-identically."""
    def run():
        trace = make_trace(1337, n_users=1200)
        harness = FleetHarness(
            "occupancy", n_replicas=2,
            autoscale=auto_spec(min_replicas=2, max_replicas=8,
                                scale_out_queue_wait_p99_s=1.5,
                                scale_in_occupancy_floor=0.2),
            warm_standbys=8,
        )
        harness.kill(70.0, "r0")
        harness.kill(160.0, "r2")
        return harness, harness.run(trace, horizon_s=900.0)

    h1, s1 = run()
    h2, s2 = run()
    assert h1.log == h2.log
    assert s1 == s2
    assert s1["completed"] == len(make_trace(1337, n_users=1200))
    assert s1["dropped"] == 0 and s1["duplicates"] == 0
    assert all(n == 1 for n in s1["redispatches"].values())
    assert s1["scale_out_events"] > 0
    assert max(s1["scale_out_reaction_s"]) <= 0.5 + 1e-6


def test_options_wire_serving_autoscale():
    opts = parse_args([
        "--serving-autoscale", "--serving-autoscale-interval", "2.5",
    ])
    assert opts.serving_autoscale is True
    assert opts.serving_autoscale_interval == 2.5
    # default OFF builds no autoscaler
    clock = SimClock()
    inj = FaultInjector(FakeCluster(), seed=1, clock=clock)
    mgr = make_operator(inj, clock)
    assert mgr.fleet_autoscaler is None
    mgr.stop()


# ----------------------------------------- failure domain (ISSUE 15)
def test_router_degraded_falls_back_to_round_robin_and_recovers():
    """ALL replicas stale at once = the monitoring plane down, not the
    fleet: nobody expires, dispatch degrades to round-robin over READY
    (in-flight bounds still honored), and the first fresh sample
    restores occupancy dispatch."""
    router, clock = make_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    clock.advance(3.0)  # both snapshots stale
    assert router.tick() == []  # degraded, NOT expired
    assert router.degraded and router.degraded_entries == 1
    assert router.replica_state("r0") == READY
    # blind round-robin (occupancy says r0 has more free; rr ignores it)
    picks = [router.submit(req(f"q{i}")) for i in range(4)]
    assert picks == ["r0", "r1", "r0", "r1"]
    assert any("router_degraded" in l for l in router.events)
    # second tick while still blind: no duplicate entry records
    router.tick()
    assert router.degraded_entries == 1
    # first fresh sample ends it
    router.observe("r0", 100, 100, 0)
    assert not router.degraded
    assert any("router_recovered" in l for l in router.events)
    # the still-stale sibling now expires NORMALLY (minority staleness)
    assert router.tick() == ["r1"]
    # its orphans moved exactly once each
    assert router.redispatches == {"q1": 1, "q3": 1}


def test_router_degraded_keyed_on_dispatchable_set_only():
    """Degraded entry/exit must consider only DISPATCHABLE replicas —
    the set _candidates() draws from.  A scrape storm covering exactly
    the READY set while a fresh drain victim keeps reporting must still
    degrade (round-robin keeps serving), the victim's heartbeats must
    NOT clear degraded, and the READY replicas must never expire to
    UNHEALTHY on its testimony — that would requeue their orphans with
    no candidate and park the FIFO on blindness."""
    router, clock = make_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    ready_replica(router, "r2")
    router.drain("r2")  # the autoscaler's scale-in victim
    assert router.submit(req("a")) == "r0"
    # storm on the READY set only: their scrape streams fail (no
    # ejection — the only clean witness is the non-dispatchable drain
    # victim) while r2's telemetry stays fresh
    for _ in range(5):
        router.scrape_failed("r0")
        router.scrape_failed("r1")
    assert router.ejections == 0
    clock.advance(3.0)  # r0/r1 stale past health_interval
    router.observe("r2", 100, 100, 0)  # drain victim still reporting
    assert router.tick() == []  # degraded, nobody expired
    assert router.degraded
    assert router.replica_state("r0") == READY
    # the drain victim's next heartbeat is not recovery evidence
    router.observe("r2", 100, 100, 0)
    assert router.degraded
    assert router.tick() == []  # still degraded: READY set unharmed
    assert router.replica_state("r0") == READY
    assert "a" not in router.redispatches
    # blind round-robin keeps serving over the READY set
    assert router.submit(req("b")) in ("r0", "r1")
    # a fresh sample from a DISPATCHABLE replica ends it
    router.observe("r0", 100, 100, 0)
    assert not router.degraded


def test_router_degraded_not_vetoed_by_never_reported_newcomer():
    """A replica mark_ready'd DURING a scrape outage (pod Ready fires;
    telemetry never can) reads fresh off its add-time anchor.  It must
    not veto degraded entry: letting it would expire the whole
    established READY set on its testimony and requeue their orphans
    toward a candidate whose snapshot=None occupancy _pick skips —
    parking the FIFO.  It still serves in the round-robin fallback."""
    router, clock = make_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    assert router.submit(req("a")) == "r0"
    clock.advance(3.0)  # the scrape plane has been down a while
    router.add_replica("r2")
    router.mark_ready("r2")  # autoscaler's newcomer: no telemetry ever
    assert router.tick() == []  # degraded, established set unharmed
    assert router.degraded
    assert router.replica_state("r0") == READY
    assert "a" not in router.redispatches
    # the newcomer is still a round-robin candidate (availability)
    picks = {router.submit(req(f"q{i}")) for i in range(3)}
    assert picks == {"r0", "r1", "r2"}


def test_router_degraded_entry_requeues_orphans_round_robin():
    """On the degraded ENTRY tick the flag must flip before any orphan
    requeue: a dead drain victim's requests expired in the same sweep
    place by round-robin, not by the fleet-wide-stale occupancy
    fiction (and carry the `degraded` dispatch reason)."""
    router, clock = make_router(health_interval=2.0)
    reasons = []
    router.on_dispatch = lambda request, rid, reason: reasons.append(
        (request.rid, rid, reason))
    # stale snapshots CLAIM r2 is emptiest — occupancy picks it
    ready_replica(router, "r0", free=10)
    ready_replica(router, "r1", free=20)
    ready_replica(router, "r2", free=100)
    assert router.submit(req("a")) == "r2"
    router.drain("r2")
    clock.advance(3.0)  # everything stale; the drain victim died too
    assert router.tick() == ["r2"]
    assert router.degraded
    # the orphan was re-placed by the DEGRADED fallback, not occupancy
    assert reasons[-1][0] == "a" and reasons[-1][2] == "degraded"
    assert reasons[-1][1] in ("r0", "r1")


def test_router_degraded_still_expires_dead_drain_victim():
    """Degraded mode spares the READY set from expiry — but a DRAINING
    replica that genuinely dies mid-outage must still expire: it is not
    a dispatch candidate (expiring it cannot park the FIFO), and its
    in-flight requests must requeue onto the round-robin READY set
    instead of stranding behind the autoscaler's inflight==0 drain wait
    for the whole storm."""
    router, clock = make_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    ready_replica(router, "r2")
    assert router.submit(req("a")) == "r0"
    router.drain("r0")  # scale-in victim, one request still in flight
    clock.advance(3.0)  # EVERYTHING stale: degraded territory
    assert router.tick() == ["r0"]  # degraded AND the victim expired
    assert router.degraded
    assert router.replica_state("r0") == UNHEALTHY
    # the trapped request moved to a READY sibling exactly once
    assert router.redispatches == {"a": 1}
    assert router.inflight("r1") + router.inflight("r2") == 1
    assert router.replica_state("r1") == READY


def test_router_degraded_honors_inflight_bound():
    router, clock = make_router(health_interval=2.0,
                                max_inflight_per_replica=1)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    clock.advance(3.0)
    router.tick()
    assert router.degraded
    assert router.submit(req("a")) == "r0"
    assert router.submit(req("b")) == "r1"
    # both bounds full: queue, never convoy — blindness does not lift
    # the router's own books
    assert router.submit(req("c")) is None
    assert router.queue_depth() == 1


def test_router_ejection_half_open_readmission_and_backoff_ladder():
    router, clock = make_router()
    router.eject_failure_threshold = 3
    router.eject_backoff_s = 4.0
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    assert router.submit(req("a")) == "r0"
    router.scrape_failed("r0")
    router.scrape_failed("r0")
    assert router.replica_state("r0") == READY  # under threshold
    router.scrape_failed("r0")
    assert router.replica_state("r0") == EJECTED
    assert router.ejections == 1
    # the orphan moved to the sibling exactly once
    assert router.redispatches == {"a": 1}
    assert router.inflight("r1") == 1
    # telemetry BEFORE the backoff window is ignored (half-open gate)
    clock.advance(1.0)
    router.observe("r0", 100, 100, 0)
    assert router.replica_state("r0") == EJECTED
    # at/after the window: the sample IS the probe — readmitted
    clock.advance(3.0)
    router.observe("r0", 100, 100, 0)
    assert router.replica_state("r0") == READY
    assert any("replica_readmitted" in l for l in router.events)
    # a second ejection doubles the backoff (capped exponential)
    for _ in range(3):
        router.scrape_failed("r0")
    assert router.replica_state("r0") == EJECTED
    assert router._replicas["r0"].eject_until - clock() == 8.0


def test_router_fleetwide_failures_never_eject_everything():
    """Ejection is a minority verdict: when EVERY replica's scrape
    stream is failing the evidence points at the monitoring plane, and
    nobody ejects (degraded mode owns that case)."""
    router, clock = make_router()
    router.eject_failure_threshold = 3
    for rid in ("r0", "r1", "r2"):
        ready_replica(router, rid)
    for _ in range(5):
        for rid in ("r0", "r1", "r2"):
            router.scrape_failed(rid)
    assert router.ejections == 0
    assert router.replicas(state=READY) == ["r0", "r1", "r2"]
    # one replica's stream healing makes the OTHERS ejectable again
    router.observe("r2", 100, 100, 0)
    for _ in range(3):
        router.scrape_failed("r0")
    assert router.replica_state("r0") == EJECTED


def test_router_mark_ready_resets_boot_failures():
    """Scrape failures racing a replica's boot (podIP up, /metrics
    listener not yet) must not carry into READY: without the reset one
    post-ready transient failure would instantly eject the newcomer —
    "N CONSECUTIVE failures" starts counting at ready."""
    router, clock = make_router()
    router.eject_failure_threshold = 3
    ready_replica(router, "r0")  # the clean witness
    router.add_replica("r2")
    for _ in range(5):
        router.scrape_failed("r2")  # boot races, state still STARTING
    router.mark_ready("r2")
    router.scrape_failed("r2")  # one transient after ready
    assert router.replica_state("r2") == READY
    assert router.ejections == 0
    router.scrape_failed("r2")
    router.scrape_failed("r2")  # ...three consecutive POST-ready: eject
    assert router.replica_state("r2") == EJECTED


def test_router_rehedges_when_the_hedge_arm_also_stalls():
    """Both copies frozen (the hedge arm froze too, both holders still
    heartbeating healthy telemetry) must not strand the request behind
    the one-live-hedge budget: the failed race settles lost, the budget
    restores, and a THIRD sibling gets the re-hedge — won+lost still
    converges to issued."""
    router, clock = hedging_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    ready_replica(router, "r2")
    seed_ttft(router, clock)
    assert router.submit(req("a")) == "r0"
    clock.advance(1.5)
    for rid in ("r0", "r1", "r2"):
        router.observe(rid, 100, 100, 0)
    router.tick()
    assert router._hedged["a"] == "r1"
    # the hedge copy ALSO goes silent past the threshold
    clock.advance(1.5)
    for rid in ("r0", "r1", "r2"):
        router.observe(rid, 100, 100, 0)  # everyone heartbeats fine
    router.tick()
    assert router._hedged["a"] == "r2"  # re-hedged to the third sibling
    assert router.hedges_issued == 2
    assert router.hedges_lost == 1  # the first race settled lost
    assert router.finish("r2", "a") is True
    assert router.hedges_won == 1  # ...and the second won at delivery
    assert router.hedges_won + router.hedges_lost == router.hedges_issued


def test_router_hedge_outcome_settles_when_a_holder_dies():
    """A holder dying mid-race must settle the hedge outcome (won+lost
    converges to issued): the ORIGINAL's death means the surviving
    hedge copy carried the request (won); the HEDGE arm's death means
    the hedge lost.  Without settlement the bench's win rate reads
    artificially low exactly in the storms hedging exists for."""
    router, clock = hedging_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    ready_replica(router, "r2")
    seed_ttft(router, clock)
    assert router.submit(req("a")) == "r0"
    clock.advance(1.5)
    for rid in ("r0", "r1", "r2"):
        router.observe(rid, 100, 100, 0)
    router.tick()
    assert router._hedged["a"] == "r1"
    # the ORIGINAL holder dies: the hedge copy is the carrier — won
    router.remove_replica("r0", requeue=True)
    assert router.hedges_won == 1 and router.hedges_lost == 0
    assert router.finish("r1", "a") is True
    # no double count at delivery (the race already settled)
    assert router.hedges_won == 1 and router.hedges_lost == 0


def test_router_ejection_witness_must_have_reported():
    """The minority-verdict witness must carry actual evidence: a
    never-reported newcomer (mark_ready mid-outage) has a clean failure
    count by vacuity, not by a working scrape stream — established
    replicas must not eject on its testimony.  Its first real sample
    makes it a qualified witness."""
    router, clock = make_router()
    router.eject_failure_threshold = 3
    ready_replica(router, "r0")
    router.add_replica("r2")
    router.mark_ready("r2")  # READY, zero failures, snapshot=None
    for _ in range(5):
        router.scrape_failed("r0")
    assert router.ejections == 0
    assert router.replica_state("r0") == READY
    # the newcomer's first sample is scrape-plane evidence: now a
    # continuing failure streak on r0 is a minority verdict
    router.observe("r2", 100, 100, 0)
    for _ in range(3):
        router.scrape_failed("r0")
    assert router.replica_state("r0") == EJECTED


def test_router_drain_fence_sticky_through_ejection():
    router, clock = make_router()
    router.eject_failure_threshold = 2
    router.eject_backoff_s = 2.0
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    router.drain("r0")
    router.scrape_failed("r0")
    router.scrape_failed("r0")
    assert router.replica_state("r0") == EJECTED
    # a drain arriving WHILE ejected only pends the fence
    router.drain("r0")
    assert router.replica_state("r0") == EJECTED
    clock.advance(2.5)
    router.observe("r0", 100, 100, 0)
    # readmitted INTO the fence, never into dispatch
    assert router.replica_state("r0") == DRAINING
    assert router.submit(req("b")) == "r1"


def test_router_dispatch_failure_replaces_and_counts_toward_ejection():
    router, clock = make_router()
    router.eject_failure_threshold = 2
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    assert router.submit(req("a")) == "r0"
    debited = router._replicas["r0"].debit_blocks
    assert debited > 0
    router.dispatch_failed("r0", "a")
    # the request never landed: re-placed immediately (on r1 — r0 just
    # failed a dispatch but is still READY below the threshold)
    assert router.inflight("r0") == 0
    assert router.inflight("r1") == 1
    # ...and the never-landed dispatch's occupancy debit is reversed —
    # a phantom debit would make r0 look full until its next heartbeat
    assert router._replicas["r0"].debit_blocks == 0
    assert router._replicas["r0"].debit_count == 0
    assert router.submit(req("b")) in ("r0", "r1")
    holder = [rid for rid in ("r0", "r1") if "b" in
              router._replicas[rid].inflight][0]
    if holder == "r0":
        router.dispatch_failed("r0", "b")
        assert router.replica_state("r0") == EJECTED


def hedging_router(**kw):
    kw.setdefault("health_interval", 100.0)  # expiry out of the way
    router, clock = make_router(**kw)
    router.enable_hedging = True
    router.hedge_min_samples = 1
    router.hedge_floor_s = 1.0
    return router, clock


def seed_ttft(router, clock, rid="r0", req_id="warm"):
    assert router.submit(req(req_id)) == rid
    clock.advance(0.2)
    router.note_first_token(rid, req_id)
    assert router.finish(rid, req_id) is True
    # clear the warm-up dispatch's debits so later picks are fair
    router.observe(rid, 100, 100, 0)


def test_hedge_issues_on_stalled_first_token_and_winner_bookkeeping():
    router, clock = hedging_router()
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    seed_ttft(router, clock)  # one TTFT sample (0.2s; floor clamps to 1)
    assert router.hedge_threshold() == 1.0
    assert router.submit(req("a")) == "r0"
    clock.advance(0.5)
    router.tick()
    assert router.hedges_issued == 0  # not overdue yet
    clock.advance(1.0)
    router.observe("r0", 100, 100, 0)
    router.observe("r1", 100, 100, 0)
    router.tick()
    assert router.hedges_issued == 1
    assert router._hedged["a"] == "r1"
    assert router.inflight("r0") == 1 and router.inflight("r1") == 1
    # only one hedge per request, ever
    clock.advance(2.0)
    router.observe("r0", 100, 100, 0)
    router.observe("r1", 100, 100, 0)
    router.tick()
    assert router.hedges_issued == 1
    # the hedge copy wins: delivered, counted, loser copy still charged
    # to ITS replica until it completes
    assert router.finish("r1", "a") is True
    assert router.hedges_won == 1 and router.hedges_lost == 0
    assert router.inflight("r0") == 1
    assert router.finish("r0", "a") is False  # duplicate, dropped
    assert router.inflight("r0") == 0


def test_hedge_progress_anchor_catches_mid_decode_freeze():
    """A request whose FIRST token arrived but whose stream then went
    silent is as overdue as one that never started: the hedge anchors
    on last progress, not first token."""
    router, clock = hedging_router()
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    seed_ttft(router, clock)
    assert router.submit(req("a")) == "r0"
    clock.advance(0.3)
    router.note_first_token("r0", "a")  # stream started...
    for _ in range(3):  # ...and keeps making progress: never hedged
        clock.advance(0.8)
        router.note_progress("r0", "a")
        router.observe("r0", 100, 100, 0)
        router.observe("r1", 100, 100, 0)
        router.tick()
    assert router.hedges_issued == 0
    # then the replica freezes mid-decode: silence past the threshold
    clock.advance(1.5)
    router.observe("r1", 100, 100, 0)
    router.tick()
    assert router.hedges_issued == 1
    assert router._hedged["a"] == "r1"


def test_hedge_loser_completion_decrements_own_replica_and_pumps():
    """The PR 14 duplicate-completion pump test, extended to hedging: a
    hedge loser completing AFTER the winner decrements in-flight on its
    OWN replica (never the winner's) and its freed slot pumps the
    queue."""
    router, clock = hedging_router(max_inflight_per_replica=1)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    seed_ttft(router, clock)
    assert router.submit(req("a")) == "r0"
    clock.advance(1.5)
    router.observe("r0", 100, 100, 0)
    router.observe("r1", 100, 100, 0)
    router.tick()  # a hedged onto r1; both bounds now full
    assert router._hedged["a"] == "r1"
    # winner (the hedge copy) delivers: r1's slot frees, r0 still holds
    # the loser copy
    assert router.finish("r1", "a") is True
    assert router.inflight("r1") == 0 and router.inflight("r0") == 1
    # new traffic fills r1; the next request has nowhere to go
    assert router.submit(req("b")) == "r1"
    assert router.submit(req("c")) is None
    assert router.queue_depth() == 1
    # the loser completes late: dropped as a duplicate, but it must
    # decrement r0's OWN in-flight (not r1's) and pump c onto r0
    assert router.finish("r0", "a") is False
    assert router.inflight("r0") == 1  # c, not a leak of a
    assert "c" in router._replicas["r0"].inflight
    assert router.inflight("r1") == 1  # b untouched
    assert router.queue_depth() == 0


def test_hedge_skips_covered_orphans_on_expiry():
    """A hedged request whose original replica dies is NOT re-dispatched
    a third time while the hedge copy is still live on a sibling — but
    the dead original DOES restore the hedge budget: the survivor is the
    only copy now, and if it is itself silent past the threshold the
    same sweep re-hedges it (a frozen survivor must never strand the
    request forever)."""
    router, clock = hedging_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    ready_replica(router, "r2")
    seed_ttft(router, clock)
    assert router.submit(req("a")) == "r0"
    clock.advance(1.5)
    for rid in ("r0", "r1", "r2"):
        router.observe(rid, 100, 100, 0)
    router.tick()
    assert router._hedged["a"] == "r1"
    # r0 (the original holder) goes silent past the health interval
    clock.advance(2.5)
    router.observe("r1", 100, 100, 0)
    router.observe("r2", 100, 100, 0)
    assert router.tick() == ["r0"]
    # NOT re-dispatched: the live hedge copy on r1 covers delivery
    assert "a" not in router.redispatches
    assert any("redispatch_skipped req=a" in l for l in router.events)
    # ...but the budget came back, and r1 (silent since the hedge went
    # out) was itself re-hedged onto r2 by the same sweep
    assert router._hedged["a"] == "r2"
    assert router.hedges_issued == 2
    assert router.finish("r1", "a") is True


def test_hedge_arm_dispatch_failure_restores_hedge_budget():
    """When the hedge COPY's dispatch never lands (connection refused),
    the request is back to one copy: the hedge ledger entry must clear,
    or a still-stalled original could never be rescued again — it would
    strand forever on a frozen replica."""
    router, clock = hedging_router()
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    ready_replica(router, "r2")
    seed_ttft(router, clock)
    assert router.submit(req("a")) == "r0"
    clock.advance(1.5)
    for rid in ("r0", "r1", "r2"):
        router.observe(rid, 100, 100, 0)
    router.tick()
    assert router._hedged["a"] == "r1"
    router.dispatch_failed("r1", "a")
    # not re-placed (the original still holds it) but re-hedgeable
    assert "a" not in router._hedged
    assert router.inflight("r0") == 1
    clock.advance(1.5)
    for rid in ("r0", "r1", "r2"):
        router.observe(rid, 100, 100, 0)
    router.tick()
    assert router.hedges_issued == 2
    assert "a" in router._hedged
    assert router.finish(router._hedged["a"], "a") is True


def test_hedge_arm_dispatch_failure_after_delivery_never_replaces():
    """A hedge arm's dispatch failure reported AFTER the other arm
    already delivered must not re-place the request: the id is in the
    completed ledger and a third dispatch would burn a whole inference
    whose completion is dropped as a duplicate (the same guard
    _requeue_orphans applies to orphan sweeps)."""
    router, clock = hedging_router()
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    ready_replica(router, "r2")
    seed_ttft(router, clock)
    assert router.submit(req("a")) == "r0"
    clock.advance(1.5)
    for rid in ("r0", "r1", "r2"):
        router.observe(rid, 100, 100, 0)
    router.tick()
    assert router._hedged["a"] == "r1"
    # the ORIGINAL delivers first; the hedge copy is still in flight
    assert router.finish("r0", "a") is True
    # ...and its dispatch failure comes back late (connection refused)
    router.dispatch_failed("r1", "a")
    # delivered request: nobody re-dispatches it, nothing is in flight
    assert all(router.inflight(rid) == 0 for rid in ("r0", "r1", "r2"))
    assert "a" not in router.redispatches
    assert not any("dispatch req=a" in e for e in router.events[-2:])


def test_hedge_arm_expiry_restores_hedge_budget():
    """The hedge copy's REPLICA expiring (covered-orphan skip) must also
    clear the ledger entry, so the same sweep can re-hedge the stalled
    original onto a healthy sibling."""
    router, clock = hedging_router(health_interval=2.0)
    ready_replica(router, "r0")
    ready_replica(router, "r1")
    ready_replica(router, "r2")
    seed_ttft(router, clock)
    assert router.submit(req("a")) == "r0"
    clock.advance(1.5)
    for rid in ("r0", "r1", "r2"):
        router.observe(rid, 100, 100, 0)
    router.tick()
    assert router._hedged["a"] == "r1"
    # r1 (the hedge arm) goes silent past the health interval while the
    # frozen original keeps heartbeating
    clock.advance(2.5)
    router.observe("r0", 100, 100, 0)
    router.observe("r2", 100, 100, 0)
    assert router.tick() == ["r1"]
    # a was covered by r0 (no third dispatch of the orphan)...
    assert "a" not in router.redispatches
    # ...and the same sweep's hedge pass re-hedged it onto r2
    assert router._hedged["a"] == "r2"
    assert router.hedges_issued == 2
    assert router.finish("r2", "a") is True


def test_fleet_chaos_soak_timeline_and_causality():
    """The kill + scrape-outage soak (ISSUE 15 acceptance): seeded
    serving faults composed by the FaultInjector — fleet-wide scrape
    storm (degraded mode entered AND exited on the timeline), a
    single-replica storm (ejection + readmission), a freeze (hedge
    rescue), a kill mid-decode (re-dispatch exactly once) — zero
    dropped, duplicate deliveries structurally zero, both logs
    byte-identical per seed, and every router DECISION in the log lands
    exactly once on the owning job's timeline, in log order."""
    from tf_operator_tpu.engine.timeline import FlightRecorder

    def run(seed, with_recorder=True):
        inj = FaultInjector(FakeCluster(), seed=seed, clock=SimClock(),
                            kubelet=False)
        inj.schedule_scrape_storm(40.0, 12.0, mode="timeout")
        inj.schedule_scrape_storm(70.0, 8.0, mode="500", replicas=["r0"])
        inj.schedule_replica_freeze(95.0, "r1")
        # r0, not the highest index: the autoscaler's occupancy-floor
        # scale-in may have drained r2 away by now — the kill must land
        # on a replica that still exists mid-traffic
        inj.schedule_replica_kill(110.0, "r0")
        recorder = (
            FlightRecorder(events_per_job=512, clock=inj.clock)
            if with_recorder else None
        )
        harness = FleetHarness(
            "occupancy", n_replicas=3, injector=inj,
            hedging=True, ejection=True,
            autoscale=auto_spec(min_replicas=2, max_replicas=6,
                                scale_out_queue_wait_p99_s=1.5,
                                scale_in_occupancy_floor=0.2),
            warm_standbys=4, recorder=recorder, job_key="default/llm",
        )
        trace = make_trace(seed, n_users=250)
        summary = harness.run(trace, horizon_s=500.0)
        return harness, summary, list(inj.log), recorder

    h1, s1, l1, rec = run(4242)
    h2, s2, l2, _ = run(4242)
    assert h1.log == h2.log and l1 == l2 and s1 == s2
    # a different seed is a different story (the injector log carries
    # only the fixed schedule labels, so only the harness log varies)
    h3, _, _, _ = run(90210)
    assert h3.log != h1.log
    # recording never writes the seeded logs (the PR 10 contract)
    h4, s4, l4, _ = run(4242, with_recorder=False)
    assert h4.log == h1.log and l4 == l1
    # zero loss; every orphan re-dispatched exactly once; duplicate
    # DELIVERIES are structurally zero (results keyed by first finish)
    assert s1["dropped"] == 0
    assert s1["completed"] == len(make_trace(4242, n_users=250))
    assert all(n == 1 for n in s1["redispatches"].values())
    # the whole ladder fired: degraded, ejection, hedging, AND the
    # kill's health-expiry re-dispatch (the kill landed mid-traffic)
    assert s1["degraded_entries"] >= 1
    assert s1["ejections"] >= 1
    assert s1["hedges_issued"] >= 1 and s1["hedges_won"] >= 1
    assert any("kill replica=r0" in l for l in h1.log)
    assert any("replica_unhealthy replica=r0" in l for l in h1.log)
    # timeline: degraded entered AND exited, ejection + readmission,
    # hedges — and each log DECISION appears exactly once, in log order
    tl = rec.timeline("default/llm")
    records = [e for e in tl["events"] if e["source"] == "router"]
    got = [e["event"] for e in records]
    for needed in ("router_degraded", "router_recovered",
                   "replica_ejected", "replica_readmitted",
                   "hedge_issued"):
        assert needed in got, f"timeline missing {needed}"
    decision_lines = [
        l for l in h1.log
        if any(k in l for k in (
            "router_degraded", "router_recovered", "replica_ejected",
            "replica_readmitted", "hedge_issued",
        ))
    ]
    assert len(decision_lines) == len(records)
    for line, record in zip(decision_lines, records):
        assert record["event"] in line
        # trigger metric + value + threshold ride the DECISION records
        if record["event"] in ("router_degraded", "hedge_issued",
                               "replica_ejected"):
            assert "trigger" in record["detail"]
            assert "threshold" in record["detail"]


def test_cli_describe_fleet_failure_columns(capsys):
    """describe's Fleet section gains scrape-age / ejected / degraded
    columns when the scrape loop and router publish them — and stays
    byte-identical when they are absent (scrape loop off)."""
    servefleet.reset_fleet_status()
    clock, inj, mgr, asc = autoscaled_operator()
    asc.report("default/llm", "llm-replica-0", free_blocks=40,
               total_blocks=100, queue_depth=2, inflight=3)
    asc.tick()
    cli = Cli(inj, recorder=mgr.recorder)
    assert cli.describe("TPUServingJob", "llm", "default") == 0
    before = capsys.readouterr().out
    assert "scrape-age" not in before
    assert "ejected" not in before and "degraded" not in before
    # the scrape loop + router publish their halves
    servefleet.note_scrape("default/llm", "llm-replica-0", 0.4, 0)
    servefleet.note_scrape("default/llm", "llm-replica-1", 7.5, 3)
    servefleet.note_router_state("default/llm", degraded=True,
                                 ejected=["llm-replica-1"])
    assert cli.describe("TPUServingJob", "llm", "default") == 0
    out = capsys.readouterr().out
    assert "degraded: yes" in out
    assert "llm-replica-0: blocks=60/100 (60%) queue=2 inflight=3 " \
           "scrape-age=0.4s" in out
    assert "llm-replica-1: no telemetry scrape-age=7.5s failures=3 " \
           "(ejected)" in out
    # publishing cleared -> byte-identical to the pre-scrape output
    servefleet.reset_fleet_status()
    asc.tick()
    asc.report("default/llm", "llm-replica-0", free_blocks=40,
               total_blocks=100, queue_depth=2, inflight=3)
    asc.tick()
    assert cli.describe("TPUServingJob", "llm", "default") == 0
    assert capsys.readouterr().out == before
    mgr.stop()
