"""Retryable-vs-permanent ExitCode handling across ALL five adapters.

The contract (api/common.py is_retryable_exit_code + the engine's ExitCode
restart branch + controllers/shared_status.py): under restartPolicy
ExitCode, a replica death with code >= 128 (signal class: SIGKILL 137,
SIGTERM 143 — the TPU preemption shapes) restarts the replica and ticks the
persisted restart counter; a 1-127 code is a permanent user error that FAILS
the job — it must neither restart nor wedge in Restarting.

One parametrized suite covers TFJob, PyTorchJob, MXJob, XGBoostJob, and
TPUJob so a status-rule regression in any single adapter cannot slip through
(pre-PR, only PyTorch and TPU had this coverage).
"""
import copy

import pytest

from tf_operator_tpu.api import common, mxnet as mxapi, pytorch as ptapi
from tf_operator_tpu.api import xgboost as xgbapi
from tf_operator_tpu.controllers import make_engine
from tf_operator_tpu.engine.controller import EngineConfig
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.fake import FakeCluster

from tests import testutil
from tests.test_engine import reconcile, run_pods, set_phase


def _template(container):
    return {
        "spec": {"containers": [{"name": container, "image": testutil.TEST_IMAGE}]}
    }


def _specs(container, **counts):
    return {
        rtype: common.ReplicaSpec(
            replicas=n, template=copy.deepcopy(_template(container))
        )
        for rtype, n in counts.items()
    }


def _tf_job():
    job = testutil.new_tfjob("ec-tf", worker=2)
    return job, "Worker", "tensorflow"


def _pt_job():
    job = ptapi.PyTorchJob(
        metadata=objects.make_meta("ec-pt") | {"uid": objects.new_uid()},
        replica_specs=_specs("pytorch", Master=1, Worker=1),
    )
    return job, "Worker", "pytorch"


def _mx_job():
    job = mxapi.MXJob(
        metadata=objects.make_meta("ec-mx") | {"uid": objects.new_uid()},
        replica_specs=_specs("mxnet", Scheduler=1, Server=1, Worker=1),
    )
    return job, "Worker", "mxnet"


def _xgb_job():
    job = xgbapi.XGBoostJob(
        metadata=objects.make_meta("ec-xgb") | {"uid": objects.new_uid()},
        replica_specs=_specs("xgboost", Master=1, Worker=1),
    )
    return job, "Worker", "xgboost"


def _tpu_job():
    job = testutil.new_tpujob("ec-tpu", accelerator_type="v4-8")
    return job, "Worker", "tpu"


BUILDERS = {
    "TFJob": _tf_job,
    "PyTorchJob": _pt_job,
    "MXJob": _mx_job,
    "XGBoostJob": _xgb_job,
    "TPUJob": _tpu_job,
}


def _setup(kind):
    cluster = FakeCluster()
    # zero backoff: these tests assert the restart DECISION per exit code,
    # not the recreation pacing (tests/test_chaos.py owns the pacing)
    engine = make_engine(kind, cluster, config=EngineConfig(restart_backoff_base=0.0))
    job, rtype, container = BUILDERS[kind]()
    job.replica_specs[rtype].restart_policy = common.RESTART_POLICY_EXIT_CODE
    cluster.create(job.kind, job.to_dict())
    job, _ = reconcile(cluster, engine, job)
    for p in cluster.list_pods():
        set_phase(cluster, p, objects.POD_RUNNING, container=container)
    job, _ = reconcile(cluster, engine, job)
    return cluster, engine, job, rtype, container


@pytest.mark.parametrize("kind", sorted(BUILDERS))
def test_permanent_exit_code_fails_job(kind):
    """Exit 1 under ExitCode: the job FAILS — no restart, no Restarting
    wedge, and the failure is terminal-sticky."""
    cluster, engine, job, rtype, container = _setup(kind)
    victim = run_pods(cluster, rtype=rtype)[0]
    set_phase(cluster, victim, objects.POD_FAILED, exit_code=1, container=container)
    job, _ = reconcile(cluster, engine, job)
    assert common.is_failed(job.status), job.status.to_dict()
    assert not common.has_condition(job.status, common.JOB_RESTARTING)
    rs = job.status.replica_statuses.get(rtype)
    assert rs is None or rs.restarts == 0


@pytest.mark.parametrize("kind", sorted(BUILDERS))
@pytest.mark.parametrize("code", [137, 143])
def test_retryable_exit_code_restarts(kind, code):
    """Exit 137 (SIGKILL/preemption/OOM) and 143 (SIGTERM) under ExitCode:
    delete-for-recreate, Restarting condition, restart counter ticks, job
    does NOT fail — and the replica set is eventually whole again."""
    cluster, engine, job, rtype, container = _setup(kind)
    total = len(cluster.list_pods())
    victim = run_pods(cluster, rtype=rtype)[0]
    set_phase(
        cluster, victim, objects.POD_FAILED, exit_code=code, container=container
    )
    job, _ = reconcile(cluster, engine, job)
    assert not common.is_failed(job.status), job.status.to_dict()
    # the Restarting condition was stamped; adapters whose other replicas
    # are still Running may re-promote Running in the same sync (demoting
    # Restarting to False), so assert presence, not current truth
    assert any(
        c.type == common.JOB_RESTARTING for c in job.status.conditions
    ), job.status.to_dict()
    assert job.status.replica_statuses[rtype].restarts == 1
    assert job.status.replica_statuses[rtype].last_restart_time
    # recreation completes on the next sync (whole-slice adapters tear down
    # every pod of the type and rebuild it atomically)
    job, _ = reconcile(cluster, engine, job)
    assert len(cluster.list_pods()) == total


@pytest.mark.parametrize("kind", sorted(BUILDERS))
def test_retryable_code_counts_toward_backoff_limit(kind):
    """The persisted restart counter feeds backoffLimit: limit=1 means the
    first retryable death restarts, the second fails the job."""
    cluster, engine, job, rtype, container = _setup(kind)
    job.run_policy.backoff_limit = 1
    raw = cluster.get(job.kind, job.namespace, job.name)
    raw["spec"].setdefault("runPolicy", {})["backoffLimit"] = 1
    cluster.update(job.kind, raw)
    victim = run_pods(cluster, rtype=rtype)[0]
    set_phase(
        cluster, victim, objects.POD_FAILED, exit_code=137, container=container
    )
    job, _ = reconcile(cluster, engine, job)  # restart #1: counter -> 1
    job, _ = reconcile(cluster, engine, job)  # limit check sees restarts >= 1
    assert common.is_failed(job.status), job.status.to_dict()
