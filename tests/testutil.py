"""Fixture builders — the analogue of the reference's
pkg/common/util/v1/testutil ({tfjob,pod,service}.go builders)."""
from __future__ import annotations

import copy
from typing import Any, Dict, List, Optional

from tf_operator_tpu.api import common, tensorflow as tfapi, pytorch as ptapi
from tf_operator_tpu.api import tpujob as tpuapi
from tf_operator_tpu.k8s import objects

TEST_IMAGE = "test-image:latest"


def free_port() -> int:
    """A kernel-assigned free port (shared by every test that launches a
    real listener).  The operator honors declared container ports, and a
    fixed default would flake on TIME_WAIT leftovers from earlier runs."""
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def tf_template(image: str = TEST_IMAGE, ports: bool = False) -> Dict[str, Any]:
    c: Dict[str, Any] = {"name": tfapi.DEFAULT_CONTAINER_NAME, "image": image}
    if ports:
        c["ports"] = [
            {"name": tfapi.DEFAULT_PORT_NAME, "containerPort": tfapi.DEFAULT_PORT}
        ]
    return {"spec": {"containers": [c]}}


def new_tfjob(
    name: str = "test-tfjob",
    namespace: str = "default",
    worker: int = 0,
    ps: int = 0,
    chief: int = 0,
    master: int = 0,
    evaluator: int = 0,
    **kwargs,
) -> tfapi.TFJob:
    """Build a TFJob with the given replica counts (reference
    testutil/tfjob.go:27-113 builder family)."""
    specs: Dict[str, common.ReplicaSpec] = {}
    for rtype, n in (
        (tfapi.REPLICA_WORKER, worker),
        (tfapi.REPLICA_PS, ps),
        (tfapi.REPLICA_CHIEF, chief),
        (tfapi.REPLICA_MASTER, master),
        (tfapi.REPLICA_EVALUATOR, evaluator),
    ):
        if n > 0:
            specs[rtype] = common.ReplicaSpec(replicas=n, template=tf_template())
    job = tfapi.TFJob(
        metadata=objects.make_meta(name, namespace) | {"uid": objects.new_uid()},
        replica_specs=specs,
        **kwargs,
    )
    return job


def new_tpujob(
    name: str = "test-tpujob",
    accelerator_type: str = "v4-32",
    num_slices: int = 1,
    namespace: str = "default",
) -> tpuapi.TPUJob:
    return tpuapi.TPUJob(
        metadata=objects.make_meta(name, namespace) | {"uid": objects.new_uid()},
        accelerator_type=accelerator_type,
        num_slices=num_slices,
        replica_specs={
            tpuapi.REPLICA_WORKER: common.ReplicaSpec(
                template={
                    "spec": {
                        "containers": [
                            {"name": tpuapi.DEFAULT_CONTAINER_NAME, "image": TEST_IMAGE}
                        ]
                    }
                }
            )
        },
    )


def set_pod_statuses(
    pods: List[Dict[str, Any]],
    phase: str,
    count: int,
    start: int = 0,
    exit_code: Optional[int] = None,
    container_name: str = tfapi.DEFAULT_CONTAINER_NAME,
) -> None:
    """Set `count` pods (from `start`) to `phase`, optionally with a
    terminated exit code (reference testutil/pod.go:57-97)."""
    for pod in pods[start : start + count]:
        pod["status"]["phase"] = phase
        if exit_code is not None:
            pod["status"]["containerStatuses"] = [
                {
                    "name": container_name,
                    "state": {"terminated": {"exitCode": exit_code}},
                }
            ]
