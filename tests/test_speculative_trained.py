"""Speculation with a TRAINED draft/target pair: the speedup is real.

Every other speculative test uses random weights, where a cheap draft
earns ~0 acceptance (its argmax is noise) — so the forward-count
reduction that motivates speculative decoding never shows up outside
the self-draft best case.  Here both models TRAIN on the same learnable
distribution until they agree, and the measured stats witness the
actual economics: a 1-layer draft proposing for a deeper target at high
acceptance, cutting target forwards by a multiple.

The data is a noisy +1 cycle (next = (cur + 1) % V, with occasional
random jumps): a single attention layer learns the rule, so the cheap
draft genuinely agrees with the target — the trained-checkpoint
situation speculation exists for, reproduced in-process in seconds.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from tf_operator_tpu.models import llama
from tf_operator_tpu.models.speculative import speculative_generate

V = 50          # small vocab: the rule is learnable in a few hundred steps
JUMP_P = 0.05   # occasional random jump keeps the task non-constant


def _data(key, batch, length):
    k1, k2, k3 = jax.random.split(key, 3)
    start = jax.random.randint(k1, (batch, 1), 0, V)
    steps = jnp.ones((batch, length - 1), jnp.int32)
    jumps = jax.random.bernoulli(k2, JUMP_P, (batch, length - 1))
    offsets = jax.random.randint(k3, (batch, length - 1), 0, V)
    inc = jnp.where(jumps, offsets, steps)
    return jnp.cumsum(jnp.concatenate([start, inc], axis=1), axis=1) % V


def _train(model, params, steps=300, batch=32, length=32, lr=3e-3,
           seed=0):
    tx = optax.adam(lr)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt, tokens):
        def loss_fn(p):
            logits = model.apply({"params": p}, tokens[:, :-1],
                                 train=False)
            tgt = tokens[:, 1:]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, tgt).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    key = jax.random.PRNGKey(seed)
    loss = None
    for i in range(steps):
        key, kd = jax.random.split(key)
        params, opt, loss = step(params, opt, _data(kd, batch, length))
    return params, float(loss)


@pytest.fixture(scope="module")
def trained_pair():
    cfg = llama.tiny(vocab_size=V, d_model=64, n_layers=3, max_len=128,
                     dtype=jnp.float32)
    d_cfg = dataclasses.replace(cfg, n_layers=1)
    target = llama.Llama(cfg)
    draft = llama.Llama(d_cfg)
    toks = jnp.zeros((1, 8), jnp.int32)
    t_params = target.init(jax.random.PRNGKey(0), toks,
                           train=False)["params"]
    d_params = draft.init(jax.random.PRNGKey(1), toks,
                          train=False)["params"]
    t_params, t_loss = _train(target, t_params, seed=2)
    d_params, d_loss = _train(draft, d_params, seed=3)
    # both learned the rule (random guessing = ln(50) ~ 3.9; the noisy
    # cycle's entropy floor is ~ H(jump) + p*ln(V) ~ 0.4)
    assert t_loss < 1.0 and d_loss < 1.2, (t_loss, d_loss)
    return target, t_params, draft, d_params


def test_trained_draft_earns_real_forward_reduction(trained_pair):
    """The economics claim itself: a trained 1-layer draft for a trained
    3-layer target cuts target forwards by >= 2x at high measured
    acceptance — with greedy output still EXACTLY the target's own.
    Wall clock is measured and printed for the record (run with -s);
    it is not hard-asserted because a loaded CI box can mask a genuine
    speedup, but the forward-count reduction that produces it is."""
    import time

    target, t_params, draft, d_params = trained_pair
    prompt = _data(jax.random.PRNGKey(9), 2, 12)
    max_new, k = 32, 4
    plain = llama.generate(target, t_params, prompt, max_new)
    jax.block_until_ready(plain)
    t0 = time.perf_counter()
    plain = llama.generate(target, t_params, prompt, max_new)
    jax.block_until_ready(plain)
    t_plain = time.perf_counter() - t0
    out, st = speculative_generate(target, t_params, draft, d_params,
                                   prompt, max_new, k=k,
                                   return_stats=True)
    t0 = time.perf_counter()
    out, st = speculative_generate(target, t_params, draft, d_params,
                                   prompt, max_new, k=k,
                                   return_stats=True)
    jax.block_until_ready(out)
    t_spec = time.perf_counter() - t0
    np.testing.assert_array_equal(np.asarray(out), np.asarray(plain))
    acc = st["accepted_drafts"] / st["proposed_drafts"]
    fwd_reduction = (max_new - 1) / st["target_forwards"]
    print(f"\ntrained pair: acceptance={acc:.3f} "
          f"target_forwards={st['target_forwards']}/{max_new - 1} "
          f"({fwd_reduction:.2f}x fewer) "
          f"wall_clock={t_plain / t_spec:.2f}x vs plain")
    assert acc > 0.5, st
    assert fwd_reduction >= 2.0, st


def test_trained_pair_serves_speculatively(trained_pair):
    """The same trained pair through speculative CONTINUOUS BATCHING:
    per-request acceptance stays high and outputs stay oracle-exact."""
    from tf_operator_tpu.models.serving import serve_loop

    target, t_params, draft, d_params = trained_pair
    prompts = [_data(jax.random.PRNGKey(20 + i), 1, n)[0]
               for i, n in enumerate((8, 13, 6, 10))]
    res = serve_loop(target, t_params, prompts, slots=2,
                     max_new_tokens=16, draft=draft,
                     draft_params=d_params, spec_k=4, steps_per_sync=2)
    total_acc = sum(r.accepted_drafts for r in res)
    total_prop = sum(r.proposed_drafts for r in res)
    assert total_acc / total_prop > 0.5, (total_acc, total_prop)
    for r, p in zip(res, prompts):
        want = llama.generate(target, t_params, p[None, :], 16)
        assert r.tokens == [int(t) for t in np.asarray(want[0])]
