"""ClusterClient (real-apiserver backend) tests.

Two tiers, mirroring what envtest gives the reference
(pkg/controller.v1/tensorflow/suite_test.go:50-76):

1. Scripted `StubTransport` — asserts the exact REST wire behavior
   (paths, verbs, label selectors, status-subresource split) and that real
   apiserver responses (409 stale RV, 404, watch MODIFIED/DELETED/BOOKMARK,
   410 Gone relist) surface with FakeCluster-identical semantics.
2. `ApiServerTransport` façade over FakeCluster — full REST round-trips
   including watch streaming (test_e2e.py additionally runs the whole
   manager e2e suite over this backend).
"""
import base64
import json
import queue
import textwrap
import threading
import time

import pytest

from tf_operator_tpu.e2e.apiserver import ApiServerTransport
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.client import (
    ClusterClient,
    load_kubeconfig,
    resource_path,
    selector_to_query,
)
from tf_operator_tpu.k8s.fake import (
    ApiError,
    ConflictError,
    FakeCluster,
    NotFoundError,
)


# ------------------------------------------------------------ scripted stub
class StubTransport:
    """Records every request; replies from a scripted queue or a handler."""

    def __init__(self):
        self.calls = []
        self.replies = []
        self.handler = None
        self.streams = []  # scripted watch streams: list of list-of-events

    def expect(self, status, body, headers=None):
        if headers is None:
            self.replies.append((status, body))  # legacy 2-tuple shape
        else:
            self.replies.append((status, body, headers))

    def request(self, method, path, query=None, body=None):
        self.calls.append((method, path, query, body))
        if self.handler:
            return self.handler(method, path, query, body)
        return self.replies.pop(0)

    def stream(self, path, query=None, cancel=None):
        self.calls.append(("WATCH", path, query, None))
        cancelled = threading.Event()
        if cancel is not None:
            cancel.append(cancelled.set)  # registered eagerly, like HttpTransport
        if not self.streams:
            def _quiet():
                while not cancelled.is_set():  # quiet watch: nothing to say
                    time.sleep(0.05)
                return
                yield  # pragma: no cover — makes this a generator

            return _quiet()
        events = self.streams.pop(0)
        if isinstance(events, ApiError):
            raise events
        return iter(events)


def make_client(namespace=""):
    t = StubTransport()
    return ClusterClient(t, namespace=namespace), t


def test_resource_paths():
    assert resource_path("Pod", "ns1", "p0") == "/api/v1/namespaces/ns1/pods/p0"
    assert resource_path("Pod", None) == "/api/v1/pods"
    assert (
        resource_path("TFJob", "ns1", "j", "status")
        == "/apis/kubeflow.org/v1/namespaces/ns1/tfjobs/j/status"
    )
    assert (
        resource_path("PodGroup", "ns1", "pg")
        == "/apis/scheduling.volcano.sh/v1beta1/namespaces/ns1/podgroups/pg"
    )
    assert (
        resource_path("Lease", "kube-system", "lock")
        == "/apis/coordination.k8s.io/v1/namespaces/kube-system/leases/lock"
    )
    with pytest.raises(ApiError):
        resource_path("Widget", "ns1")


def test_selector_query_is_sorted_and_joined():
    assert selector_to_query({"b": "2", "a": "1"}) == "a=1,b=2"
    assert selector_to_query(None) is None


def test_create_posts_to_namespace_collection():
    c, t = make_client()
    pod = objects.make_pod("p0", namespace="ns1")
    t.expect(201, {**pod, "metadata": {**pod["metadata"], "uid": "u1"}})
    out = c.create_pod(pod)
    method, path, _, body = t.calls[0]
    assert (method, path) == ("POST", "/api/v1/namespaces/ns1/pods")
    assert body["metadata"]["name"] == "p0"
    assert out["metadata"]["uid"] == "u1"


def test_conflict_on_create_maps_to_conflict_error():
    c, t = make_client()
    t.expect(409, {"kind": "Status", "message": "already exists", "code": 409})
    with pytest.raises(ConflictError):
        c.create_pod(objects.make_pod("p0"))


def test_get_404_maps_to_not_found():
    c, t = make_client()
    t.expect(404, {"kind": "Status", "message": "not found", "code": 404})
    with pytest.raises(NotFoundError):
        c.get_pod("default", "ghost")


def test_update_stale_rv_maps_to_conflict():
    c, t = make_client()
    t.expect(409, {"kind": "Status", "message": "rv conflict", "code": 409})
    pod = objects.make_pod("p0")
    pod["metadata"]["resourceVersion"] = "5"
    with pytest.raises(ConflictError):
        c.update_pod(pod)
    method, path, _, _ = t.calls[0]
    assert (method, path) == ("PUT", "/api/v1/namespaces/default/pods/p0")


def test_job_update_splits_status_subresource():
    """One FakeCluster-style update = main PUT + /status PUT carrying the RV
    the main PUT returned (apiserver drops status on main-resource writes)."""
    c, t = make_client()
    job = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {"name": "j", "namespace": "ns1", "resourceVersion": "3"},
        "spec": {"x": 1},
        "status": {"conditions": [{"type": "Running"}]},
    }
    main_reply = {**job, "metadata": {**job["metadata"], "resourceVersion": "4"}}
    status_reply = {**job, "metadata": {**job["metadata"], "resourceVersion": "5"}}
    t.expect(200, main_reply)
    t.expect(200, status_reply)
    out = c.update("TFJob", job)
    (m1, p1, _, b1), (m2, p2, _, b2) = t.calls
    assert (m1, p1) == ("PUT", "/apis/kubeflow.org/v1/namespaces/ns1/tfjobs/j")
    assert (m2, p2) == (
        "PUT",
        "/apis/kubeflow.org/v1/namespaces/ns1/tfjobs/j/status",
    )
    assert b2["metadata"]["resourceVersion"] == "4"  # RV from the main PUT
    assert out["metadata"]["resourceVersion"] == "5"


def test_update_without_status_is_single_put():
    c, t = make_client()
    job = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {"name": "j", "namespace": "ns1"},
        "spec": {},
    }
    t.expect(200, job)
    c.update("TFJob", job)
    assert len(t.calls) == 1


def test_list_sends_label_selector_and_restores_kind():
    c, t = make_client()
    t.expect(
        200,
        {
            "kind": "PodList",
            "metadata": {"resourceVersion": "9"},
            "items": [{"metadata": {"name": "p0", "namespace": "d"}}],
        },
    )
    out = c.list_pods(namespace="d", selector={"job-name": "j", "a": "b"})
    _, path, query, _ = t.calls[0]
    assert path == "/api/v1/namespaces/d/pods"
    assert query == {"labelSelector": "a=b,job-name=j"}
    assert out[0]["kind"] == "Pod"


def test_list_all_namespaces_when_unscoped():
    c, t = make_client(namespace="")
    t.expect(200, {"items": []})
    c.list("Service")
    assert t.calls[0][1] == "/api/v1/services"


def test_list_uses_client_namespace_scope():
    c, t = make_client(namespace="kubeflow")
    t.expect(200, {"items": []})
    c.list("Service")
    assert t.calls[0][1] == "/api/v1/namespaces/kubeflow/services"


def test_delete_404_maps_to_not_found():
    c, t = make_client()
    t.expect(404, {"message": "gone", "code": 404})
    with pytest.raises(NotFoundError):
        c.delete_pod("d", "p0")


def test_read_pod_log():
    c, t = make_client()
    t.expect(200, "line1\nline2")
    assert c.read_pod_log("d", "p0") == "line1\nline2"
    assert t.calls[0][1] == "/api/v1/namespaces/d/pods/p0/log"


def test_record_event_posts_v1_event_and_swallows_errors():
    c, t = make_client()
    t.expect(201, {})
    job = {"kind": "TFJob", "metadata": {"name": "j", "namespace": "d", "uid": "u"}}
    c.record_event(job, "Warning", "Reason", "msg")
    method, path, _, body = t.calls[0]
    assert (method, path) == ("POST", "/api/v1/namespaces/d/events")
    assert body["involvedObject"] == {
        "kind": "TFJob",
        "name": "j",
        "namespace": "d",
        "uid": "u",
    }
    assert body["type"] == "Warning" and body["reason"] == "Reason"
    # a failing event write must not raise (observability never fails reconcile)
    t.expect(500, {"message": "boom"})
    c.record_event(job, "Normal", "R", "m")


# --------------------------------------------------------------- watch loop
def _wait_until(pred, timeout=5.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.01)
    raise TimeoutError(what)


def test_watch_dispatches_and_handles_bookmark_and_gone():
    t = StubTransport()
    pod1 = {"kind": "Pod", "metadata": {"name": "a", "namespace": "d", "resourceVersion": "2"}}
    pod2 = {"kind": "Pod", "metadata": {"name": "a", "namespace": "d", "resourceVersion": "3"}}

    lists = queue.Queue()
    lists.put({"metadata": {"resourceVersion": "1"}, "items": []})
    lists.put({"metadata": {"resourceVersion": "7"}, "items": []})

    def handler(method, path, query, body):
        assert path == "/api/v1/pods"
        return 200, lists.get(timeout=5)

    t.handler = handler
    # stream 1: ADDED, BOOKMARK(rv=5), MODIFIED, then ERROR 410 -> relist
    t.streams.append(
        [
            {"type": "ADDED", "object": pod1},
            {"type": "BOOKMARK", "object": {"metadata": {"resourceVersion": "5"}}},
            {"type": "MODIFIED", "object": pod2},
            {"type": "ERROR", "object": {"kind": "Status", "code": 410}},
        ]
    )
    # stream 2 (after relist): a late DELETED for the same pod — the relist
    # diff already reported it gone, so this replay must be suppressed
    t.streams.append(
        [{"type": "DELETED", "object": {**pod2, "metadata": {**pod2["metadata"], "resourceVersion": "8"}}}]
    )

    c = ClusterClient(t)
    got = []
    c.subscribe("Pod", lambda et, obj: got.append((et, obj["metadata"]["resourceVersion"])))
    # the relist (rv 7, no items) diff-reports the DELETED itself
    _wait_until(lambda: len(got) == 3, what="3 watch events")
    assert got == [("ADDED", "2"), ("MODIFIED", "3"), ("DELETED", "7")]
    # the relist happened (two list calls) and the second watch resumed from
    # the fresh list RV
    watch_calls = [q for (m, p, q, b) in t.calls if m == "WATCH"]
    assert watch_calls[0]["resourceVersion"] == "1"
    assert watch_calls[1]["resourceVersion"] == "7"
    c.close()


def test_watch_410_gap_repaired_by_relist_diff():
    """Events lost while the watch was expired MUST still reach subscribers:
    the relist diffs against delivered state (client-go Reflector replace
    semantics) — a relist that only re-pins the rv would hide the gap
    forever, breaking FakeCluster's lossless-subscribe contract."""
    t = StubTransport()
    pod_a1 = {"kind": "Pod", "metadata": {"name": "a", "namespace": "d", "resourceVersion": "2"}}
    pod_a2 = {"kind": "Pod", "metadata": {"name": "a", "namespace": "d", "resourceVersion": "6"}}
    pod_b = {"kind": "Pod", "metadata": {"name": "b", "namespace": "d", "resourceVersion": "5"}}
    pod_c = {"kind": "Pod", "metadata": {"name": "c", "namespace": "d", "resourceVersion": "3"}}

    lists = queue.Queue()
    # seed list: pod c exists before subscribe (must NOT be dispatched)
    lists.put({"metadata": {"resourceVersion": "1"}, "items": [dict(pod_c)]})
    # relist after the 410 gap: a modified, b created, c deleted
    lists.put({"metadata": {"resourceVersion": "7"}, "items": [dict(pod_a2), dict(pod_b)]})
    t.handler = lambda m, p, q, b: (200, lists.get(timeout=5))
    # stream 1: ADDED a, then the watch dies with 410
    t.streams.append(
        [
            {"type": "ADDED", "object": pod_a1},
            {"type": "ERROR", "object": {"kind": "Status", "code": 410}},
        ]
    )

    c = ClusterClient(t)
    got = []
    c.subscribe("Pod", lambda et, obj: got.append((et, obj["metadata"]["name"])))
    _wait_until(lambda: len(got) >= 4, what="gap-repair events")
    assert got[0] == ("ADDED", "a")
    # diff events, order-insensitive between kinds of change
    repair = set(got[1:4])
    assert repair == {("MODIFIED", "a"), ("ADDED", "b"), ("DELETED", "c")}
    c.close()


def test_close_unblocks_quiet_watch_thread():
    """close() must abort a stream blocked with nothing to deliver — the
    cancel hook — instead of leaking the thread and its connection."""
    t = StubTransport()
    t.handler = lambda *a: (200, {"metadata": {"resourceVersion": "1"}, "items": []})
    c = ClusterClient(t)
    c.subscribe("Pod", lambda et, obj: None)
    loop = c._watches["Pod"]
    c.close()
    loop._thread.join(timeout=3.0)
    assert not loop._thread.is_alive(), "watch thread must exit on close()"


def test_unsubscribe_stops_loop_when_last_handler_removed():
    t = StubTransport()
    t.handler = lambda *a: (200, {"metadata": {"resourceVersion": "1"}, "items": []})
    c = ClusterClient(t)
    h = lambda et, obj: None  # noqa: E731
    c.subscribe("Pod", h)
    assert "Pod" in c._watches
    c.unsubscribe("Pod", h)
    assert "Pod" not in c._watches


# ------------------------------------------------------------ retry layer
class _Rng:
    """Degenerate rng: uniform(a, b) -> b, so computed backoff is the cap
    and assertions are exact."""

    def uniform(self, a, b):
        return b


def retry_client(**policy_kw):
    from tf_operator_tpu.k8s.client import RetryPolicy

    t = StubTransport()
    sleeps = []
    c = ClusterClient(
        t,
        retry=RetryPolicy(**{"base_delay": 0.1, "max_delay": 5.0, **policy_kw}),
        sleep=sleeps.append,
        rng=_Rng(),
    )
    return c, t, sleeps


def test_retry_on_500_then_success():
    from tf_operator_tpu.engine import metrics

    before = metrics.API_RETRIES.get({"reason": "500"})
    c, t, sleeps = retry_client()
    t.expect(500, {"message": "boom"})
    t.expect(503, {"message": "still boom"})
    t.expect(200, {"metadata": {"name": "p0"}})
    assert c.get_pod("d", "p0")["metadata"]["name"] == "p0"
    assert len(t.calls) == 3
    # full jitter with the degenerate rng: cap = base * 2^attempt
    assert sleeps == [pytest.approx(0.1), pytest.approx(0.2)]
    assert metrics.API_RETRIES.get({"reason": "500"}) == before + 1


def test_retry_honors_retry_after_header():
    c, t, sleeps = retry_client()
    t.expect(429, {"message": "slow down"}, {"Retry-After": "3"})
    t.expect(200, {"metadata": {"name": "p0"}})
    c.get_pod("d", "p0")
    assert sleeps == [3.0], "server-provided Retry-After overrides backoff"


def test_terminal_errors_are_not_retried():
    c, t, _ = retry_client()
    t.expect(404, {"message": "nope"})
    with pytest.raises(NotFoundError):
        c.get_pod("d", "ghost")
    assert len(t.calls) == 1
    t.calls.clear()
    t.expect(409, {"message": "stale"})
    with pytest.raises(ConflictError):
        c.update_pod(objects.make_pod("p0"))
    assert len(t.calls) == 1, "a 409 must not be replayed verbatim"


def test_connection_reset_is_retried():
    c, t, sleeps = retry_client()
    state = {"n": 0}

    def flaky(method, path, query, body):
        state["n"] += 1
        if state["n"] == 1:
            raise ConnectionResetError("peer reset")
        return 200, {"items": []}

    t.handler = flaky
    assert c.list_pods() == []
    assert state["n"] == 2 and len(sleeps) == 1


def test_delete_replay_after_reset_treats_404_as_success():
    """A DELETE whose first attempt committed before the reply was lost
    must not surface the replay's 404 as NotFoundError — the delete
    succeeded (client-go convention).  A FIRST-attempt 404 still raises."""
    c, t, _ = retry_client()
    state = {"n": 0}

    def committed_then_lost(method, path, query, body):
        state["n"] += 1
        if state["n"] == 1:
            raise ConnectionResetError("reply lost after commit")
        return 404, {"message": "not found"}

    t.handler = committed_then_lost
    c.delete_pod("d", "p0")  # no raise: replayed 404 == success
    assert state["n"] == 2
    t.handler = lambda *a: (404, {"message": "never existed"})
    with pytest.raises(NotFoundError):
        c.delete_pod("d", "ghost")


def test_retry_gives_up_after_attempt_budget():
    c, t, sleeps = retry_client(max_attempts=3)
    t.handler = lambda *a: (503, {"message": "down"})
    with pytest.raises(ApiError) as e:
        c.get_pod("d", "p0")
    assert e.value.code == 503
    assert len(t.calls) == 3  # initial + 2 replays
    assert len(sleeps) == 2


def test_retry_respects_request_deadline():
    c, t, sleeps = retry_client(deadline=0.05, max_delay=40.0)
    t.handler = lambda *a: (500, {"message": "down"})
    with pytest.raises(ApiError):
        c.get_pod("d", "p0")
    # first computed delay (0.1) already exceeds the 50ms budget: no sleep
    assert sleeps == [] and len(t.calls) == 1


def test_classification_matrix():
    from tf_operator_tpu.k8s.fake import (
        is_retryable_api_error,
        is_transient_api_error,
    )

    for code in (429, 500, 502, 503, 504, 408):
        assert is_retryable_api_error(ApiError(code, "x")), code
    for exc in (ApiError(400, "x"), NotFoundError(), ConflictError()):
        assert not is_retryable_api_error(exc), exc
    assert is_retryable_api_error(ConnectionResetError())
    assert is_retryable_api_error(TimeoutError())
    # permanent local misconfiguration must NOT look like an outage...
    import ssl

    assert not is_retryable_api_error(
        ssl.SSLCertVerificationError("bad CA bundle")
    )
    assert not is_retryable_api_error(FileNotFoundError("client.key"))
    # ...but a TLS stream dropped mid-read IS one (OSError, yet neither
    # ConnectionError nor a cert problem)
    assert is_retryable_api_error(ssl.SSLEOFError("EOF in violation"))
    # conflicts ARE transient at workqueue level (fresh reconcile cures)
    assert is_transient_api_error(ConflictError())
    assert not is_transient_api_error(NotFoundError())
    assert not is_transient_api_error(ValueError("not an api error"))


# ------------------------------------------------------------- kubeconfig
def test_load_kubeconfig_token_and_inline_certs(tmp_path):
    ca = base64.b64encode(b"CA PEM").decode()
    cfg_file = tmp_path / "kubeconfig"
    cfg_file.write_text(
        textwrap.dedent(
            f"""
            apiVersion: v1
            kind: Config
            current-context: ctx
            contexts:
            - name: ctx
              context: {{cluster: c1, user: u1}}
            clusters:
            - name: c1
              cluster:
                server: https://10.0.0.1:6443
                certificate-authority-data: {ca}
            users:
            - name: u1
              user:
                token: sekrit-token
            """
        )
    )
    kc = load_kubeconfig(str(cfg_file))
    assert kc.server == "https://10.0.0.1:6443"
    assert kc.token == "sekrit-token"
    with open(kc.ca_cert_file, "rb") as fh:
        assert fh.read() == b"CA PEM"


def test_load_kubeconfig_missing_context_raises(tmp_path):
    cfg_file = tmp_path / "kc"
    cfg_file.write_text("apiVersion: v1\ncurrent-context: nope\ncontexts: []\n")
    with pytest.raises(ValueError, match="context"):
        load_kubeconfig(str(cfg_file))


# ----------------------------------------------------- façade integration
@pytest.fixture()
def rest_cluster():
    fake = FakeCluster()
    transport = ApiServerTransport(fake)
    client = ClusterClient(transport)
    yield fake, client
    client.close()
    transport.close()


def test_facade_crud_round_trip(rest_cluster):
    fake, c = rest_cluster
    pod = objects.make_pod("p0", namespace="d", labels={"job-name": "j"})
    created = c.create_pod(pod)
    assert created["metadata"]["uid"]
    assert c.get_pod("d", "p0")["metadata"]["name"] == "p0"
    assert [objects.name_of(p) for p in c.list_pods(selector={"job-name": "j"})] == ["p0"]
    assert c.list_pods(selector={"job-name": "other"}) == []
    c.delete_pod("d", "p0")
    with pytest.raises(NotFoundError):
        c.get_pod("d", "p0")


def test_facade_duplicate_create_conflicts(rest_cluster):
    _, c = rest_cluster
    c.create_pod(objects.make_pod("p0"))
    with pytest.raises(ConflictError):
        c.create_pod(objects.make_pod("p0"))


def test_facade_stale_rv_update_conflicts(rest_cluster):
    _, c = rest_cluster
    created = c.create_pod(objects.make_pod("p0"))
    c.update_pod(created)  # bumps RV server-side
    with pytest.raises(ConflictError):
        c.update_pod(created)  # stale RV


def test_facade_status_subresource_is_isolated(rest_cluster):
    """Main PUT keeps stored status; /status PUT keeps stored spec."""
    _, c = rest_cluster
    job = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {"name": "j", "namespace": "d"},
        "spec": {"v": 1},
    }
    created = c.create("TFJob", job)
    # write a status through the split-update path
    # schema-complete condition: the facade validates writes against the
    # CRD schema (type+status are required on conditions)
    created["status"] = {"conditions": [{"type": "Created", "status": "True"}]}
    updated = c.update("TFJob", created)
    assert updated["status"]["conditions"][0]["type"] == "Created"
    # a spec-only writer that carries NO status must not clobber it
    fresh = c.get("TFJob", "d", "j")
    fresh["spec"]["v"] = 2
    fresh.pop("status")
    after = c.update("TFJob", fresh)
    final = c.get("TFJob", "d", "j")
    assert final["spec"]["v"] == 2
    assert final["status"]["conditions"][0]["type"] == "Created", (
        "main-resource PUT must not wipe the status subresource"
    )
    assert after["metadata"]["resourceVersion"]


def test_facade_watch_delivers_post_subscribe_events(rest_cluster):
    fake, c = rest_cluster
    pre = objects.make_pod("pre", namespace="d")
    fake.create_pod(pre)  # before subscribe: must NOT be delivered
    got = []
    c.subscribe("Pod", lambda et, obj: got.append((et, objects.name_of(obj))))
    time.sleep(0.05)
    post = objects.make_pod("post", namespace="d")
    c.create_pod(post)
    live = c.get_pod("d", "post")
    c.update_pod(live)
    c.delete_pod("d", "post")
    _wait_until(lambda: len(got) >= 3, what="watch events")
    assert got[:3] == [("ADDED", "post"), ("MODIFIED", "post"), ("DELETED", "post")]


def test_facade_watch_survives_410_expiry(rest_cluster):
    fake, c = rest_cluster
    transport = c.transport
    got = []
    c.subscribe("Pod", lambda et, obj: got.append((et, objects.name_of(obj))))
    c.create_pod(objects.make_pod("a", namespace="d"))
    _wait_until(lambda: ("ADDED", "a") in got, what="first event")
    transport.expire_watches()  # kills the live watch with 410 Gone
    time.sleep(0.1)
    c.create_pod(objects.make_pod("b", namespace="d"))
    _wait_until(lambda: ("ADDED", "b") in got, what="event after relist")


def test_facade_generate_name(rest_cluster):
    _, c = rest_cluster
    ev = {
        "apiVersion": "v1",
        "kind": "Event",
        "metadata": {"generateName": "j.", "namespace": "d"},
        "type": "Normal",
        "involvedObject": {"name": "j"},
    }
    out = c.create("Event", ev)
    assert out["metadata"]["name"].startswith("j.")
    assert len(out["metadata"]["name"]) > len("j.")


def test_facade_record_event_and_events_for(rest_cluster):
    _, c = rest_cluster
    job = {"kind": "TFJob", "metadata": {"name": "j", "namespace": "d", "uid": "u"}}
    c.record_event(job, "Warning", "Unhealthy", "bad")
    c.record_event(job, "Normal", "Created", "ok")
    warnings = c.events_for("j", "Warning")
    assert len(warnings) == 1 and warnings[0]["reason"] == "Unhealthy"
    assert len(c.events_for("j")) == 2
    assert c.events_for("other") == []


def test_facade_pod_log_passthrough(rest_cluster):
    fake, c = rest_cluster
    fake.create_pod(objects.make_pod("p0", namespace="d"))
    fake.append_pod_log("d", "p0", "hello")
    fake.append_pod_log("d", "p0", "world")
    assert c.read_pod_log("d", "p0") == "hello\nworld"


def test_facade_cluster_scoped_round_trip(rest_cluster):
    """A CRD POSTed through the facade must be found by the namespace-less
    GET (cluster-scoped kinds key under the empty namespace)."""
    fake, c = rest_cluster
    c.create("CustomResourceDefinition", {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": "tfjobs.kubeflow.org"},
    })
    got = c.get("CustomResourceDefinition", "", "tfjobs.kubeflow.org")
    assert got["metadata"]["name"] == "tfjobs.kubeflow.org"
    c.delete("CustomResourceDefinition", "", "tfjobs.kubeflow.org")
    with pytest.raises(NotFoundError):
        c.get("CustomResourceDefinition", "", "tfjobs.kubeflow.org")


def test_events_for_namespace_scoping(rest_cluster):
    """Same-named jobs in different namespaces must not leak each other's
    events into `describe` (namespace-aware filter on both backends)."""
    fake, c = rest_cluster
    for ns in ("team-a", "team-b"):
        job = {"kind": "TFJob",
               "metadata": {"name": "mnist", "namespace": ns}}
        fake.record_event(job, "Normal", "JobCreated", f"created in {ns}")
        c.record_event(job, "Normal", "JobCreated", f"created in {ns}")
    a = fake.events_for("mnist", namespace="team-a")
    assert len(a) == 1 and "team-a" in a[0]["message"]
    a = c.events_for("mnist", namespace="team-a")
    assert len(a) == 1 and "team-a" in a[0]["message"]
    assert len(c.events_for("mnist")) == 2


def test_apiserver_enforces_crd_schema_on_write():
    """The facade rejects schema-invalid CR writes with 422 Invalid like a
    real apiserver validating against the CRD's structural schema —
    'runs unmodified on a real apiserver' must include the rejections."""
    from tf_operator_tpu.e2e.apiserver import ApiServerTransport
    from tf_operator_tpu.k8s.client import ClusterClient
    from tf_operator_tpu.k8s.fake import ApiError, FakeCluster

    backing = FakeCluster()
    transport = ApiServerTransport(backing)
    cluster = ClusterClient(transport)
    try:
        bad = {
            "apiVersion": "kubeflow.org/v1", "kind": "TFJob",
            "metadata": {"name": "schema-bad", "namespace": "default"},
            "spec": {"tfReplicaSpecs": {"Worker": {
                "replicas": -2,                 # minimum: 0
                "restartPolicy": "Sometimes",   # not in enum
                "template": {"spec": {"containers": [
                    {"name": "tensorflow", "image": "x"}]}},
            }}},
        }
        with pytest.raises(ApiError) as e:
            cluster.create("TFJob", bad)
        assert e.value.code == 422
        assert "restartPolicy" in str(e.value)
        assert backing.list("TFJob", namespace="default") == []

        # a valid body stores; an invalid main-resource UPDATE also 422s
        bad["spec"]["tfReplicaSpecs"]["Worker"].update(
            replicas=2, restartPolicy="Never")
        cluster.create("TFJob", bad)
        doc = cluster.get("TFJob", "default", "schema-bad")
        doc["spec"]["tfReplicaSpecs"]["Worker"]["restartPolicy"] = "Nope"
        with pytest.raises(ApiError) as e:
            cluster.update("TFJob", doc)
        assert e.value.code == 422
        kept = backing.get("TFJob", "default", "schema-bad")
        assert kept["spec"]["tfReplicaSpecs"]["Worker"]["restartPolicy"] == "Never"

        # POST clears client-sent status (apiserver create semantics for
        # status-subresource kinds) instead of validating or storing it
        with_status = {
            "apiVersion": "kubeflow.org/v1", "kind": "TFJob",
            "metadata": {"name": "round-trip", "namespace": "default"},
            "spec": {"tfReplicaSpecs": {"Worker": {
                "replicas": 1,
                "template": {"spec": {"containers": [
                    {"name": "tensorflow", "image": "x"}]}},
            }}},
            "status": {"conditions": [{"type": "Created"}]},  # incomplete
        }
        cluster.create("TFJob", with_status)
        assert backing.get(
            "TFJob", "default", "round-trip").get("status") in (None, {})

        # a /status write with a schema-invalid condition 422s — the
        # stored status stays valid by induction, so main-resource
        # writers are never blamed for status they didn't author
        doc = cluster.get("TFJob", "default", "round-trip")
        doc["status"] = {"conditions": [{"type": "Created"}]}  # no 'status'
        with pytest.raises(ApiError) as e:
            cluster.update("TFJob", doc)
        assert e.value.code == 422
    finally:
        cluster.close()
        transport.close()


def test_facade_phase_profile():
    """enable_profile() makes the façade account its request time by phase
    (parse / validate / store.* / watch_fanout) so the fake-vs-REST bench
    gap is a measured breakdown, not an attribution (VERDICT r4 weak #6).
    Off by default: profile stays None and request() takes the unprofiled
    path."""
    from tf_operator_tpu.e2e.apiserver import ApiServerTransport
    from tf_operator_tpu.k8s.client import ClusterClient
    from tf_operator_tpu.k8s.fake import FakeCluster

    backing = FakeCluster()
    transport = ApiServerTransport(backing)
    assert transport.profile is None
    cluster = ClusterClient(transport)
    try:
        job = {
            "apiVersion": "kubeflow.org/v1", "kind": "TFJob",
            "metadata": {"name": "prof", "namespace": "default"},
            "spec": {"tfReplicaSpecs": {"Worker": {
                "replicas": 1,
                "template": {"spec": {"containers": [
                    {"name": "tensorflow", "image": "x"}]}},
            }}},
        }
        cluster.create("TFJob", job)  # unprofiled: must not record
        assert transport.profile is None

        transport.enable_profile()
        job["metadata"]["name"] = "prof2"
        cluster.create("TFJob", job)
        cluster.get("TFJob", "default", "prof2")
        cluster.list("TFJob", namespace="default")
        cluster.delete("TFJob", "default", "prof2")

        s = transport.profile_summary()
        for phase in ("request", "parse", "validate", "store.create",
                      "store.get", "store.list", "store.delete",
                      "watch_fanout"):
            assert phase in s, f"missing phase {phase}"
            assert s[phase]["calls"] >= 1
            assert s[phase]["total_ms"] >= 0.0
        # one create was validated, one create stored
        assert s["validate"]["calls"] == 1
        assert s["store.create"]["calls"] == 1
        # shares: the DISJOINT decomposition — parse + validate +
        # store_minus_fanout + watch_fanout + other — covers 100%
        # (raw store.* shares still CONTAIN their nested fanout time,
        # so summing those alongside watch_fanout would double-count)
        shares = s["shares_pct"]
        disjoint = ("parse", "validate", "store_minus_fanout",
                    "watch_fanout", "other")
        accounted = sum(shares.get(k, 0.0) for k in disjoint)
        assert 95.0 <= accounted <= 105.0
        assert all(0.0 <= v <= 100.0 for v in shares.values())
    finally:
        cluster.close()
        transport.close()


def test_facade_update_status_single_put_fast_path(rest_cluster):
    """ClusterClient.update_status — the engine's hot-path status write —
    is ONE /status PUT: spec stays untouched (even when the body carries
    none), stale resourceVersion conflicts, invalid status 422s through
    the status-only validator, and the write is visible to watchers."""
    fake, c = rest_cluster
    job = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {"name": "fast", "namespace": "default"},
        "spec": {"tfReplicaSpecs": {"Worker": {
            "replicas": 1,
            "template": {"spec": {"containers": [
                {"name": "tensorflow", "image": "x"}]}},
        }}},
    }
    created = c.create("TFJob", job)
    # minimal engine-shaped body: identity + rv + status, NO spec
    body = {
        "apiVersion": "kubeflow.org/v1",
        "kind": "TFJob",
        "metadata": {
            "name": "fast", "namespace": "default",
            "resourceVersion": created["metadata"]["resourceVersion"],
        },
        "status": {"conditions": [{"type": "Created", "status": "True"}]},
    }
    written = c.update_status("TFJob", body)
    assert written["status"]["conditions"][0]["type"] == "Created"
    stored = fake.get("TFJob", "default", "fast")
    assert stored["spec"]["tfReplicaSpecs"]["Worker"]["replicas"] == 1, (
        "a spec-less status write must not touch the stored spec"
    )
    # stale rv -> conflict (the engine's conflict-retry path depends on it)
    with pytest.raises(ConflictError):
        c.update_status("TFJob", body)
    # invalid status -> 422 from the status-only validator
    from tf_operator_tpu.k8s.fake import ApiError

    bad = dict(body)
    bad["metadata"] = dict(body["metadata"])
    bad["metadata"]["resourceVersion"] = written["metadata"]["resourceVersion"]
    bad["status"] = {"conditions": [{"type": "Created"}]}  # missing 'status'
    with pytest.raises(ApiError) as e:
        c.update_status("TFJob", bad)
    assert e.value.code == 422 and "status" in str(e.value)


# --------------------------------------------------- pooled keep-alive pool
@pytest.fixture()
def socket_cluster():
    """ClusterClient -> pooled HttpTransport -> real TCP socket ->
    HTTP/1.1 HttpApiServer -> FakeCluster: the full wire path the pool
    exists for."""
    from tf_operator_tpu.e2e.http_apiserver import HttpApiServer
    from tf_operator_tpu.k8s.client import HttpTransport, KubeConfig

    server = HttpApiServer().start()
    transport = HttpTransport(KubeConfig(server=server.url), pool_size=4)
    client = ClusterClient(transport)
    yield server, transport, client
    client.close()
    transport.close()
    server.stop()


def _conn_counters():
    from tf_operator_tpu.engine import metrics

    return (
        metrics.TRANSPORT_CONNECTIONS_CREATED.get(),
        metrics.TRANSPORT_CONNECTIONS_REUSED.get(),
    )


def test_pool_reuses_one_connection_for_serial_requests(socket_cluster):
    _, transport, client = socket_cluster
    created0, reused0 = _conn_counters()
    client.create_pod(objects.make_pod("p0", namespace="d"))
    for _ in range(9):
        client.get_pod("d", "p0")
    created, reused = _conn_counters()
    assert created - created0 == 1, "10 serial requests must share 1 socket"
    assert reused - reused0 == 9


def test_pool_bounds_parallel_requests_to_pool_size(socket_cluster):
    """Thread-safety + the bound: 8 threads x 6 requests each never hold
    more than pool_size sockets, and the pool serves every request."""
    _, transport, client = socket_cluster
    client.create_pod(objects.make_pod("p0", namespace="d"))
    created0, reused0 = _conn_counters()
    errors = []

    def worker():
        try:
            for _ in range(6):
                client.get_pod("d", "p0")
        except Exception as e:  # noqa: BLE001 — surfaced via the assert
            errors.append(e)

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert errors == []
    created, reused = _conn_counters()
    assert created - created0 <= transport.pool_size
    assert reused - reused0 >= 48 - transport.pool_size
    assert len(transport._idle) <= transport.pool_size


def test_pool_retires_errored_connection_and_replays_stale(socket_cluster):
    """A mid-request server failure must retire that socket — never hand it
    to the next caller — and a request that died on a REUSED socket before
    any response bytes is replayed once on a fresh connection, so pooling
    never introduces failures the per-request transport didn't have."""
    server, transport, client = socket_cluster
    client.create_pod(objects.make_pod("p0", namespace="d"))
    client.get_pod("d", "p0")  # socket now pooled + warm
    created0, _ = _conn_counters()

    real_request = server.transport.request
    state = {"bombs": 1}

    def sabotaged(method, path, query=None, body=None):
        if state["bombs"] > 0:
            state["bombs"] -= 1
            # handler thread dies mid-exchange -> socket aborted under the
            # client, exactly like a connection reset
            raise RuntimeError("chaos: handler killed")
        return real_request(method, path, query, body)

    server.transport.request = sabotaged
    try:
        # rides the poisoned pooled socket, dies without response bytes,
        # replays on a fresh connection, succeeds — caller sees nothing
        assert client.get_pod("d", "p0")["metadata"]["name"] == "p0"
    finally:
        server.transport.request = real_request
    created, _ = _conn_counters()
    assert created - created0 == 1, "the retired socket was replaced by one fresh dial"
    # the pool is not poisoned: follow-up requests reuse cleanly
    for _ in range(3):
        client.get_pod("d", "p0")
    assert _conn_counters()[0] == created

    # POST is NEVER transport-replayed, even on a reused socket: the
    # first attempt may have committed server-side (PR 3 invariant; the
    # reconcile level is the idempotent replay) — the stale-socket death
    # surfaces as a retryable connection error instead
    state["bombs"] = 1
    server.transport.request = sabotaged
    try:
        with pytest.raises((ConnectionError, ApiError)):
            client.create_pod(objects.make_pod("p1", namespace="d"))
    finally:
        server.transport.request = real_request
    # and the failure still did not poison the pool
    assert client.get_pod("d", "p0")["metadata"]["name"] == "p0"


def test_watch_streams_never_enter_the_pool(socket_cluster):
    """stream() owns a private connection for its whole life: it never
    comes from — or returns to — the request pool, and its cancel hook
    closes that private socket."""
    server, transport, client = socket_cluster
    client.create_pod(objects.make_pod("seed", namespace="d"))
    idle_before = len(transport._idle)
    _, reused0 = _conn_counters()

    got = []
    client.subscribe("Pod", lambda et, obj: got.append((et, objects.name_of(obj))))
    client.create_pod(objects.make_pod("post", namespace="d"))
    deadline = time.monotonic() + 5.0
    while ("ADDED", "post") not in got and time.monotonic() < deadline:
        time.sleep(0.01)
    assert ("ADDED", "post") in got
    # the live watch holds no pool slot and parked nothing in the pool
    assert len(transport._idle) <= idle_before + 1  # +1: the create above
    loop_thread = client._watches["Pod"]._thread
    client.close()  # cancel hook must close the watch's private socket
    loop_thread.join(timeout=3.0)
    assert not loop_thread.is_alive()
    # request path still healthy afterwards
    assert client.get_pod("d", "seed")["metadata"]["name"] == "seed"


def test_http11_watch_is_close_framed_and_survives_410(socket_cluster):
    """The HTTP/1.1 server keeps per-request responses keep-alive framed
    but still ends watch streams by closing the connection (410 semantics
    byte-compatible with the old HTTP/1.0 behavior)."""
    server, transport, client = socket_cluster
    got = []
    client.subscribe("Pod", lambda et, obj: got.append((et, objects.name_of(obj))))
    client.create_pod(objects.make_pod("a", namespace="d"))
    _wait_until(lambda: ("ADDED", "a") in got, what="first event")
    server.transport.expire_watches()  # 410 Gone ends the stream
    time.sleep(0.1)
    client.create_pod(objects.make_pod("b", namespace="d"))
    _wait_until(lambda: ("ADDED", "b") in got, what="event after relist")


def test_sdk_patch_path_rides_the_pooled_transport(socket_cluster):
    """The SDK's read-merge-write PATCH emulation (GET + PUT per attempt,
    plus conflict retries) must reuse the one pooled transport — zero new
    connections once the pool is warm, no per-call construction."""
    from tf_operator_tpu.sdk.client import TFJobClient

    _, transport, client = socket_cluster
    sdk = TFJobClient(client)
    sdk.create({
        "apiVersion": "kubeflow.org/v1", "kind": "TFJob",
        "metadata": {"name": "sdkjob", "namespace": "default"},
        "spec": {"tfReplicaSpecs": {"Worker": {
            "replicas": 1,
            "template": {"spec": {"containers": [
                {"name": "tensorflow", "image": "x"}]}},
        }}},
    })
    created0, reused0 = _conn_counters()
    for n in (3, 2, 3):
        sdk.scale("sdkjob", n)
    created, reused = _conn_counters()
    assert created == created0, "a warm pool needs no new connections"
    assert reused - reused0 >= 6, "every GET/PUT attempt reused a socket"


def test_reconcile_burst_creates_at_most_pool_size_connections(socket_cluster):
    """The acceptance claim: one reconcile burst in steady state creates at
    most pool-size request connections (plus one dedicated connection per
    watch stream) while the reuse counter tracks request volume."""
    from tf_operator_tpu.cmd.manager import OperatorManager
    from tf_operator_tpu.cmd.options import ServerOptions
    from tf_operator_tpu.controllers.registry import EnabledSchemes

    server, transport, client = socket_cluster
    created0, reused0 = _conn_counters()
    manager = OperatorManager(
        client,
        ServerOptions(enabled_schemes=EnabledSchemes(["TFJob"])),
    )
    manager.factory.start_all()
    try:
        assert manager.factory.wait_for_cache_sync()
        for i in range(6):
            client.create("TFJob", {
                "apiVersion": "kubeflow.org/v1", "kind": "TFJob",
                "metadata": {"name": f"burst-{i}", "namespace": "default"},
                "spec": {"tfReplicaSpecs": {"Worker": {
                    "replicas": 2,
                    "template": {"spec": {"containers": [
                        {"name": "tensorflow", "image": "x"}]}},
                }}},
            })
        manager.process_until_idle(timeout=30.0)
    finally:
        manager.stop()
    created, reused = _conn_counters()
    # 3 watch streams (TFJob/Pod/Service) each own one dedicated conn
    watches = 3
    assert created - created0 <= transport.pool_size + watches, (
        created - created0
    )
    assert reused - reused0 > 2 * (created - created0), (
        "reuse must dominate creation across a reconcile burst"
    )


def test_fake_update_status_merges_and_conflicts():
    """FakeCluster.update_status mirrors the façade: status merged onto the
    stored object, spec kept, rv conflict on stale writes, MODIFIED
    notified (informer caches see status changes)."""
    fake = FakeCluster()
    fake.create("TFJob", {
        "apiVersion": "kubeflow.org/v1", "kind": "TFJob",
        "metadata": {"name": "m", "namespace": "default"},
        "spec": {"keep": True},
    })
    seen = []
    fake.subscribe("TFJob", lambda et, obj: seen.append(et))
    stored = fake.get("TFJob", "default", "m")
    out = fake.update_status("TFJob", {
        "metadata": {"name": "m", "namespace": "default",
                     "resourceVersion": stored["metadata"]["resourceVersion"]},
        "status": {"startTime": "2026-08-03T00:00:00Z"},
    })
    assert out["spec"] == {"keep": True}
    assert out["status"]["startTime"] == "2026-08-03T00:00:00Z"
    assert seen == ["MODIFIED"]
    with pytest.raises(ConflictError):
        fake.update_status("TFJob", {
            "metadata": {"name": "m", "namespace": "default",
                         "resourceVersion": stored["metadata"]["resourceVersion"]},
            "status": {},
        })
