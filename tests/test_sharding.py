"""Sharded control plane (ISSUE 6): rendezvous routing, per-slot leases
with an injectable clock, crash failover with re-adopt, fencing-token
rejection of zombie writes, and the APF-style admission layer.

The full storm scenarios live in tests/test_chaos.py (shard-crash soak,
threaded-stream determinism); this module covers the mechanisms one at a
time.
"""
import threading
import time
from collections import Counter

import pytest

from tf_operator_tpu.api import common
from tf_operator_tpu.cmd.leader import LeaseLock
from tf_operator_tpu.cmd.manager import ShardedOperator
from tf_operator_tpu.cmd.options import ServerOptions
from tf_operator_tpu.controllers.registry import EnabledSchemes, make_engine
from tf_operator_tpu.engine import metrics
from tf_operator_tpu.engine.sharding import (
    FENCE_ANNOTATION,
    ShardRouter,
    fence_token,
    parse_fence_token,
)
from tf_operator_tpu.k8s import objects
from tf_operator_tpu.k8s.chaos import DeterministicQueue, FaultInjector, SimClock
from tf_operator_tpu.k8s.fake import (
    ApiError,
    FakeCluster,
    StaleFencingTokenError,
)

from tests import testutil


# ------------------------------------------------------------- rendezvous
def test_rendezvous_balance_and_minimal_movement():
    """Satellite: growing N->N+1 reassigns ~1/(N+1) of jobs (and only ever
    TO the new slot); shrinking by one slot moves exactly that slot's jobs
    and nobody else's."""
    uids = [f"uid-{i}" for i in range(4000)]
    r8, r9, r7 = ShardRouter(8), ShardRouter(9), ShardRouter(7)
    a8 = {u: r8.slot_for(u) for u in uids}

    counts = Counter(a8.values())
    fair = len(uids) / 8
    assert set(counts) == set(range(8))
    assert all(0.6 * fair < c < 1.4 * fair for c in counts.values()), counts

    movers = [u for u in uids if r9.slot_for(u) != a8[u]]
    assert 0 < len(movers) / len(uids) < 2 / 9
    assert all(r9.slot_for(u) == 8 for u in movers), (
        "growing may only move keys to the NEW slot"
    )

    for u in uids:
        if a8[u] != 7:
            assert r7.slot_for(u) == a8[u], (
                "removing slot 7 must not move keys owned elsewhere"
            )


def test_rendezvous_is_stable_and_uidless_objects_land_on_slot_zero():
    r = ShardRouter(4)
    assert r.slot_for("abc") == ShardRouter(4).slot_for("abc")
    assert r.slot_for(None) == 0
    assert r.slot_for("") == 0


def test_fence_token_round_trip():
    tok = fence_token("default", "tpu-operator-shard-3", 7)
    assert parse_fence_token(tok) == ("default", "tpu-operator-shard-3", 7)
    assert parse_fence_token("garbage") is None
    assert parse_fence_token("a/b:notanint") is None


# ------------------------------------------------------------- lease lock
def test_lease_lock_simclock_expiry_and_generation():
    """Satellite: the elector core is clock-injectable — a SimClock expires
    leases with zero real sleeps — and every NEW holding bumps the fencing
    generation while in-lease renewals keep it."""
    cluster = FakeCluster()
    clock = SimClock()
    a = LeaseLock(cluster, "a", "slot-0", lease_duration=10.0, clock=clock)
    b = LeaseLock(cluster, "b", "slot-0", lease_duration=10.0, clock=clock)
    assert a.try_acquire_or_renew()
    assert a.generation == 1 and a.token == "default/slot-0:1"
    assert not b.try_acquire_or_renew() and b.lost_to_other

    clock.advance(5.0)
    assert a.try_acquire_or_renew() and a.generation == 1  # renew keeps gen

    clock.advance(11.0)  # a's lease lapses on the sim clock
    assert b.try_acquire_or_renew()
    assert b.generation == 2 and b.token == "default/slot-0:2"
    # the zombie keeps its cached stale token — exactly what fencing rejects
    assert a.token == "default/slot-0:1"
    assert not a.try_acquire_or_renew() and a.lost_to_other


def test_lease_lock_survives_transient_store_errors_inside_window():
    """A 500 storm on the Lease kind must not shed ownership while the
    lease window is still open — only an observed other holder or local
    expiry does."""
    inner = FakeCluster()
    clock = SimClock()
    inj = FaultInjector(inner, seed=0, clock=clock, kubelet=False)
    lock = LeaseLock(inj, "a", "slot-0", lease_duration=20.0, clock=clock)
    assert lock.try_acquire_or_renew()
    inj.schedule_storm(1, 10, fault="500")
    inj.step(5.0)  # inside the storm
    assert not lock.try_acquire_or_renew()
    assert not lock.lost_to_other and not lock.locally_expired()
    inj.step(10.0)  # storm over, still inside the lease window
    assert lock.try_acquire_or_renew() and lock.generation == 1


def test_elector_sheds_leadership_at_renew_deadline_not_lease_duration():
    """The threaded elector has NO fencing on its writes, so it must stop
    leading once renews have failed for renew_deadline — holding on until
    the full lease_duration would overlap it with the standby that legally
    acquires the lapsed lease."""
    from tf_operator_tpu.cmd.leader import LeaderElector

    cluster = FakeCluster()
    clock = SimClock()
    elector = LeaderElector(
        cluster, "a", lease_duration=15.0, renew_deadline=5.0, clock=clock,
    )
    assert elector._try_acquire_or_renew()
    clock.advance(4.0)  # renews failing, but inside the renew deadline
    assert not (
        elector.lock.lost_to_other
        or clock() - elector.lock.last_renew > elector.renew_deadline
    ), "must keep trying inside the renew window"
    clock.advance(2.0)  # 6s since last successful renew > renew_deadline=5
    assert clock() - elector.lock.last_renew > elector.renew_deadline, (
        "past the renew deadline the run loop's shed condition must fire "
        "(well before lease_duration at 15s)"
    )


def test_forget_job_clears_tracked_expectation_keys():
    """Deleted (not moved) jobs must not leak their _exp_keys entry — the
    single-process default never calls disown_job, so forget_job is the
    only reclaim point under job churn."""
    cluster = FakeCluster()
    engine = make_engine("TFJob", cluster)
    job = testutil.new_tfjob("churn", worker=1)
    cluster.create("TFJob", job.to_dict())
    fresh = engine.adapter.from_dict(cluster.get("TFJob", "default", "churn"))
    engine.reconcile(fresh)
    assert fresh.key in engine._exp_keys
    engine.forget_job(fresh.key)
    assert engine._exp_keys == {}


# ---------------------------------------------------------------- fencing
def _lease_obj(name, generation, holder="shard-x"):
    return {
        "apiVersion": "coordination.k8s.io/v1",
        "kind": "Lease",
        "metadata": {"name": name, "namespace": "default"},
        "spec": {
            "holderIdentity": holder,
            "leaseDurationSeconds": 15.0,
            "renewTime": 0,
            "generation": generation,
        },
    }


def _status_body(stored, token=None):
    meta = {
        "name": stored["metadata"]["name"],
        "namespace": stored["metadata"]["namespace"],
        "resourceVersion": stored["metadata"]["resourceVersion"],
    }
    if token:
        meta["annotations"] = {FENCE_ANNOTATION: token}
    return {
        "apiVersion": stored["apiVersion"],
        "kind": stored["kind"],
        "metadata": meta,
        "status": {"conditions": []},
    }


def test_fake_store_rejects_stale_fencing_token_and_counts_it():
    cluster = FakeCluster()
    cluster.create("Lease", _lease_obj("slot-0", generation=2))
    job = testutil.new_tfjob("fenced", worker=1)
    cluster.create(job.kind, job.to_dict())
    stored = cluster.get("TFJob", "default", "fenced")

    before = metrics.FENCING_REJECTIONS.get({"kind": "TFJob"})
    with pytest.raises(StaleFencingTokenError):
        cluster.update_status(
            "TFJob", _status_body(stored, token="default/slot-0:1")
        )
    assert metrics.FENCING_REJECTIONS.get({"kind": "TFJob"}) == before + 1
    # the stale write left no trace
    assert cluster.get("TFJob", "default", "fenced")["status"] == stored.get(
        "status", {}
    )
    # the CURRENT generation is accepted, and a token naming a Lease that
    # does not exist passes (fencing only in force where a lock says who
    # owns)
    cluster.update_status(
        "TFJob", _status_body(stored, token="default/slot-0:2")
    )
    stored = cluster.get("TFJob", "default", "fenced")
    cluster.update_status(
        "TFJob", _status_body(stored, token="default/no-such-lease:1")
    )


def test_rest_facade_propagates_fencing_rejection_as_403():
    """The fencing check lives in the backing store, so the REST façade —
    and therefore the live-cluster client path — inherits it."""
    from tf_operator_tpu.e2e.apiserver import ApiServerTransport

    backing = FakeCluster()
    transport = ApiServerTransport(backing)
    backing.create("Lease", _lease_obj("slot-1", generation=3))
    job = testutil.new_tfjob("restfence", worker=1)
    backing.create(job.kind, job.to_dict())
    stored = backing.get("TFJob", "default", "restfence")

    status, payload = transport.request(
        "PUT",
        "/apis/kubeflow.org/v1/namespaces/default/tfjobs/restfence/status",
        body=_status_body(stored, token="default/slot-1:2"),
    )
    assert status == 403, payload
    assert "stale" in payload["message"]
    transport.close()


# ------------------------------------------------------- sharded operator
def _sharded_harness(shards, seed=0, lease_duration=20.0, kubelet=True):
    inner = FakeCluster()
    clock = SimClock()
    inj = FaultInjector(inner, seed=seed, clock=clock, kubelet=kubelet)
    opts = ServerOptions(enabled_schemes=EnabledSchemes(["TFJob"]))
    op = ShardedOperator(
        inj, opts, shard_count=shards, engine_kwargs={"clock": clock},
        clock=clock, lease_duration=lease_duration, note=inj.note,
    )
    for s in op.shards:
        for ctl in s.manager.controllers.values():
            ctl.queue = DeterministicQueue()
    op.start(workers=False)
    return inner, clock, inj, op


def _drain(op, budget=200):
    for _ in range(budget):
        busy = False
        for s in op.shards:
            if s.crashed:
                continue
            for ctl in s.manager.controllers.values():
                key = ctl.queue.get(timeout=0)
                if key is None:
                    continue
                busy = True
                try:
                    ctl._sync_guarded(key)
                finally:
                    ctl.queue.done(key)
        if not busy:
            return


def _settle(inj, op, rounds=6, dt=2.0):
    for _ in range(rounds):
        inj.step(dt)
        op.tick()
        _drain(op)


def test_events_route_to_exactly_one_owning_shard():
    """Each job is driven by its rendezvous owner and ONLY by it: every
    other shard's queue and engine never see the job."""
    inner, clock, inj, op = _sharded_harness(4)
    names = {}
    for i in range(8):
        job = testutil.new_tfjob(f"route{i}", worker=1)
        job.metadata["uid"] = f"uid-{i}"
        names[f"route{i}"] = op.router.slot_for(f"uid-{i}")
        inj.create("TFJob", job.to_dict())
    _settle(inj, op)

    for name, slot in names.items():
        stored = inner.get("TFJob", "default", name)
        status = common.JobStatus.from_dict(stored.get("status"))
        assert common.is_running(status), (name, stored.get("status"))
        key = f"default/{name}"
        for s in op.shards:
            engine = s.manager.controllers["TFJob"].engine
            saw = key in engine._rv_seen
            assert saw == (s.index == slot), (
                f"{name} (slot {slot}) synced by shard {s.index}"
            )
    assert len(inner.list_pods()) == 8
    # the ownership gauges add up
    op.tick()
    total = sum(
        metrics.SHARD_JOBS_OWNED.get({"shard": s.id, "kind": "TFJob"})
        for s in op.shards
    )
    assert total == 8
    # queue depth is per-shard when sharded: a kind-only key would be
    # last-writer-wins across N shards' controllers
    for s in op.shards:
        assert metrics.WORKQUEUE_DEPTH.get(
            {"kind": "TFJob", "shard": s.id}
        ) == 0


def test_crash_failover_readopts_and_zombie_write_is_fenced():
    """The zombie scenario end to end: shard A crashes mid-flight, its
    slot's lease lapses, shard B takes over (generation bump), re-adopts
    and keeps driving the job — including booking a preemption restart —
    then A wakes up still believing and its status write is REJECTED with
    the stale fencing token, leaving B's exact restart counter in place."""
    inner, clock, inj, op = _sharded_harness(2, lease_duration=10.0)
    uid = next(u for u in (f"u{i}" for i in range(50))
               if op.router.slot_for(u) == 0)
    job = testutil.new_tfjob("zomb", worker=1)
    job.replica_specs["Worker"].restart_policy = common.RESTART_POLICY_EXIT_CODE
    job.metadata["uid"] = uid
    inj.create("TFJob", job.to_dict())
    _settle(inj, op)
    stored = inner.get("TFJob", "default", "zomb")
    assert common.is_running(common.JobStatus.from_dict(stored["status"]))

    failovers_before = metrics.SHARD_FAILOVERS.get(
        {"slot": "0", "shard": "shard-1"}
    )
    op.crash_shard(0)
    clock.advance(11.0)  # slot-0 lease lapses on the sim clock
    _settle(inj, op)
    assert op.slot_owner(0) == 1
    assert metrics.SHARD_FAILOVERS.get(
        {"slot": "0", "shard": "shard-1"}
    ) == failovers_before + 1

    # B drives a real preemption restart after the takeover
    assert inj.kill_pod("default", "zomb-worker-0", 137)
    _settle(inj, op, rounds=10, dt=5.0)
    stored = inner.get("TFJob", "default", "zomb")
    rs = common.ReplicaStatus.from_dict(
        stored["status"]["replicaStatuses"]["Worker"]
    )
    assert rs.restarts == 1 and rs.active == 1, stored["status"]

    # the zombie wakes up still believing it owns slot 0 and tries to
    # write status with its cached generation-1 token
    op.resume_shard(0)
    zombie_engine = op.shards[0].manager.controllers["TFJob"].engine
    assert op.shards[0].handle.owns_uid(uid), "zombie must still believe"
    # ...but belief is not proof: its lease window lapsed, so the
    # side-effect gate already refuses before the store has to fence
    assert not op.shards[0].handle.may_act(uid)
    fresh = zombie_engine.adapter.from_dict(
        inner.get("TFJob", "default", "zomb")
    )
    import copy

    old_status = copy.deepcopy(fresh.status)
    fresh.status.replica_statuses["Worker"].restarts = 99  # the clobber
    rejections_before = metrics.FENCING_REJECTIONS.get({"kind": "TFJob"})
    with pytest.raises(ApiError) as exc:
        zombie_engine._write_status(fresh, old_status)
    assert "stale" in str(exc.value)
    assert metrics.FENCING_REJECTIONS.get(
        {"kind": "TFJob"}
    ) == rejections_before + 1
    # the restart counter stayed exact — the zombie changed nothing
    stored = inner.get("TFJob", "default", "zomb")
    rs = common.ReplicaStatus.from_dict(
        stored["status"]["replicaStatuses"]["Worker"]
    )
    assert rs.restarts == 1
    # and the zombie's next lease tick discovers the loss and disowns
    op.tick()
    assert not op.shards[0].handle.owns_uid(uid)


def test_zombie_dispatch_issues_no_pod_mutations():
    """A resumed zombie with a parked workqueue key must not reconcile:
    only the final status write is store-fenced, so a zombie sync that
    reached the engine could create/delete pods unfenced against the job
    the new owner is driving.  The may_act gate at dispatch refuses
    (requeue, not disown — a recovered renew must resume), the next
    lease tick disowns, and the dispatch after that drops cleanly."""
    inner, clock, inj, op = _sharded_harness(2, lease_duration=10.0)
    uid = next(u for u in (f"u{i}" for i in range(50))
               if op.router.slot_for(u) == 0)
    job = testutil.new_tfjob("zomb2", worker=1)
    job.metadata["uid"] = uid
    inj.create("TFJob", job.to_dict())
    _settle(inj, op)
    assert common.is_running(common.JobStatus.from_dict(
        inner.get("TFJob", "default", "zomb2")["status"]
    ))

    op.crash_shard(0)
    clock.advance(11.0)
    _settle(inj, op)
    assert op.slot_owner(0) == 1

    # a worker pod vanishes: any shard that reconciles now WOULD create
    # a replacement — exactly the unfenced mutation a zombie must not make
    inner.delete("Pod", "default", "zomb2-worker-0")
    pods_before = len(inner.list_pods())
    creates_before = inj.pod_creates.get("default/zomb2", 0)

    op.resume_shard(0)
    zombie_ctl = op.shards[0].manager.controllers["TFJob"]
    zombie_ctl.enqueue("default/zomb2")  # the parked key
    key = zombie_ctl.queue.get(timeout=0)
    assert key == "default/zomb2"
    try:
        zombie_ctl._sync_guarded(key)
    finally:
        zombie_ctl.queue.done(key)
    assert len(inner.list_pods()) == pods_before, "zombie created a pod"
    assert inj.pod_creates.get("default/zomb2", 0) == creates_before
    # refused but NOT disowned: the key is requeued (transient ladder)
    assert len(zombie_ctl.queue) == 1

    # the zombie's next lease tick observes the new holder and disowns;
    # the requeued key then drops cleanly at dispatch
    op.tick()
    assert not op.shards[0].handle.owns_uid(uid)
    key = zombie_ctl.queue.get(timeout=0)
    try:
        zombie_ctl._sync_guarded(key)
    finally:
        zombie_ctl.queue.done(key)
    assert len(zombie_ctl.queue) == 0

    # the real owner replaces the missing pod and the job re-converges
    _settle(inj, op, rounds=10, dt=5.0)
    assert len(inner.list_pods()) == pods_before + 1
    assert common.is_running(common.JobStatus.from_dict(
        inner.get("TFJob", "default", "zomb2")["status"]
    ))


def test_second_operator_instance_cannot_steal_leases():
    """Lease holder identities are instance-qualified: a second operator
    process (rolling-update overlap, accidental replica, standby) whose
    shard has the same index must NOT be mistaken for the current holder
    — its acquire fails while the lease is live, and its eventual
    takeover bumps the fencing generation so the old instance's writes
    are rejected."""
    inner = FakeCluster()
    clock = SimClock()
    opts = ServerOptions(enabled_schemes=EnabledSchemes(["TFJob"]))
    mk = lambda: ShardedOperator(  # noqa: E731
        inner, opts, shard_count=1, enable_leases=True,
        clock=clock, lease_duration=10.0,
    )
    a, b = mk(), mk()
    assert a.instance_id != b.instance_id
    a.start(workers=False)
    assert 0 in a.shards[0].owned_slots
    gen_a = a.shards[0].locks[0].generation
    assert gen_a == 1

    # B comes up while A's lease is live: same shard index, different
    # instance — B must neither acquire at start nor via its sweep
    b.start(workers=False)
    assert 0 not in b.shards[0].owned_slots
    b.tick()
    assert 0 not in b.shards[0].owned_slots
    lease = inner.get("Lease", "default", "tpu-operator-shard-0")
    assert lease["spec"]["holderIdentity"] == f"{a.instance_id}/shard-0"

    # A dies (stops renewing); after the lease lapses B takes over WITH
    # a generation bump — A's cached token is now stale and fenced
    clock.advance(11.0)
    b.tick()
    assert 0 in b.shards[0].owned_slots
    assert b.shards[0].locks[0].generation == gen_a + 1
    lease = inner.get("Lease", "default", "tpu-operator-shard-0")
    assert lease["spec"]["holderIdentity"] == f"{b.instance_id}/shard-0"
    a.factory.stop_all()
    b.factory.stop_all()


def test_clean_stop_releases_leases_for_immediate_takeover():
    """Voluntary shutdown must release held slot leases: the replacement
    instance is a DIFFERENT holder identity, so without the release every
    clean rolling restart would leave all jobs undriven for a full lease
    duration."""
    inner = FakeCluster()
    clock = SimClock()
    opts = ServerOptions(enabled_schemes=EnabledSchemes(["TFJob"]))
    a = ShardedOperator(
        inner, opts, shard_count=2, enable_leases=True,
        clock=clock, lease_duration=30.0,
    )
    a.start(workers=False)
    assert {0, 1} == a.shards[0].owned_slots | a.shards[1].owned_slots
    a.stop()

    # no clock advance: the replacement must acquire IMMEDIATELY
    b = ShardedOperator(
        inner, opts, shard_count=2, enable_leases=True,
        clock=clock, lease_duration=30.0,
    )
    b.start(workers=False)
    assert 0 in b.shards[0].owned_slots
    assert 1 in b.shards[1].owned_slots
    # each takeover bumped the generation: a's cached tokens are stale
    for slot in (0, 1):
        assert b.shards[slot].locks[slot].generation == 2
    b.stop()


def test_disowned_job_rebuilds_expectations_never_leaks():
    """Satellite: a moved job's in-flight expectations are deleted on
    disown — the slot's next holder starts from a clean ledger instead of
    being gated by a dead shard's unobserved creates."""
    inner = FakeCluster()
    clock = SimClock()
    inj = FaultInjector(inner, seed=0, clock=clock, kubelet=False)
    # drop the pod ADDED events so the creates stay unobserved in-flight
    inj.schedule_watch_outage(0, 100, kinds=("Pod", "Service"))
    inj.step(0.5)  # enter the outage
    engine = make_engine("TFJob", inj, clock=clock)
    job = testutil.new_tfjob("mover", worker=2)
    inj.create("TFJob", job.to_dict())
    fresh = engine.adapter.from_dict(inner.get("TFJob", "default", "mover"))
    engine.reconcile(fresh)
    assert len(inner.list_pods()) == 2
    assert not engine.satisfied_expectations(fresh), (
        "outage must leave the creates unobserved"
    )
    engine.disown_job(fresh.key)
    assert engine.satisfied_expectations(fresh)
    assert engine._exp_keys == {}, "tracked keys must not leak"


def test_sharded_single_shard_has_no_leases_and_no_fence():
    """shards=1 is the pre-shard engine: static ownership, no Lease
    objects, unfenced status writes."""
    inner, clock, inj, op = _sharded_harness(1)
    assert not op.enable_leases
    job = testutil.new_tfjob("solo", worker=1)
    inj.create("TFJob", job.to_dict())
    _settle(inj, op)
    assert inner.list("Lease") == []
    stored = inner.get("TFJob", "default", "solo")
    assert common.is_running(common.JobStatus.from_dict(stored["status"]))
    assert (stored["metadata"].get("annotations") or {}).get(
        FENCE_ANNOTATION
    ) is None


# -------------------------------------------------------------------- APF
def test_apf_noisy_tenant_capped_while_quiet_tenant_stays_bounded():
    """ISSUE 6 acceptance: a tenant flooding the admission layer gets 429s
    (queue_full) while another tenant's queue waits stay bounded — the
    fair-share dispatcher alternates flows, so the quiet tenant never
    waits behind the noisy tenant's whole backlog."""
    from tf_operator_tpu.e2e.http_apiserver import (
        FairFlowController,
        RejectedError,
    )

    metrics.APF_REJECTED.reset()
    metrics.APF_QUEUE_WAIT.reset()
    apf = FairFlowController(
        seats=2, queue_limit=4, queue_timeout=10.0, retry_after=0.25
    )
    hold = 0.005
    noisy_rejected = []

    def noisy():
        for _ in range(15):
            try:
                apf.acquire("noisy")
            except RejectedError:
                noisy_rejected.append(1)
                continue
            try:
                time.sleep(hold)
            finally:
                apf.release()

    threads = [threading.Thread(target=noisy) for _ in range(8)]
    for t in threads:
        t.start()
    quiet_waits = []
    for _ in range(10):
        t0 = time.monotonic()
        apf.acquire("quiet")
        quiet_waits.append(time.monotonic() - t0)
        try:
            time.sleep(hold)
        finally:
            apf.release()
    for t in threads:
        t.join()

    assert noisy_rejected, "the noisy tenant must hit its queue cap"
    assert metrics.APF_REJECTED.get(
        {"flow": "noisy", "reason": "queue_full"}
    ) == len(noisy_rejected)
    assert metrics.APF_REJECTED.get(
        {"flow": "quiet", "reason": "queue_full"}
    ) == 0
    # every quiet request was admitted with a bounded wait: well under the
    # noisy backlog's total service time
    assert max(quiet_waits) < 1.0, quiet_waits
    assert metrics.APF_QUEUE_WAIT.count({"flow": "quiet"}) >= 1


def test_http_apiserver_apf_rejects_with_retry_after_header():
    import http.client

    from tf_operator_tpu.e2e.http_apiserver import (
        FairFlowController,
        HttpApiServer,
        flow_of,
    )

    assert flow_of("/api/v1/namespaces/team-a/pods") == "team-a"
    assert flow_of("/apis/kubeflow.org/v1/tfjobs") == "cluster"

    apf = FairFlowController(seats=1, queue_limit=0, retry_after=0.75)
    server = HttpApiServer(apf=apf).start()
    try:
        apf.acquire("hog")  # occupy the only seat out-of-band
        conn = http.client.HTTPConnection(server.host, server.port, timeout=5)
        conn.request("GET", "/api/v1/namespaces/default/pods")
        resp = conn.getresponse()
        body = resp.read()
        assert resp.status == 429, body
        assert resp.getheader("Retry-After") == "0.75"
        apf.release()
        conn.request("GET", "/api/v1/namespaces/default/pods")
        resp = conn.getresponse()
        resp.read()
        assert resp.status == 200
        conn.close()
    finally:
        server.stop()


def test_apf_client_retry_ladder_rides_through_a_burst():
    """End to end over a real socket: the operator's ClusterClient retries
    the 429 (honoring Retry-After) and completes once the seat frees."""
    from tf_operator_tpu.e2e.http_apiserver import (
        FairFlowController,
        HttpApiServer,
    )
    from tf_operator_tpu.k8s.client import (
        ClusterClient,
        HttpTransport,
        KubeConfig,
        RetryPolicy,
    )

    apf = FairFlowController(seats=1, queue_limit=0, retry_after=0.1)
    server = HttpApiServer(apf=apf).start()
    transport = HttpTransport(KubeConfig(server=server.url))
    client = ClusterClient(
        transport, retry=RetryPolicy(base_delay=0.05, deadline=10.0)
    )
    try:
        apf.acquire("hog")
        timer = threading.Timer(0.4, apf.release)
        timer.start()
        pods = client.list_pods()  # retried until the seat frees
        assert pods == []
        timer.cancel()
    finally:
        client.close()
        transport.close()
        server.stop()
