"""tpu-jobs user CLI (sdk/cli.py): submit/get/list/wait/pods/logs/delete
against a FakeCluster with a real engine reconcile in between."""
import json

import pytest
import yaml

from tf_operator_tpu.controllers.registry import make_engine
from tf_operator_tpu.k8s.fake import FakeCluster, NotFoundError
from tf_operator_tpu.sdk.cli import Cli, make_parser, resolve_kind, run

TFJOB = {
    "apiVersion": "kubeflow.org/v1",
    "kind": "TFJob",
    "metadata": {"name": "mnist", "namespace": "default"},
    "spec": {
        "tfReplicaSpecs": {
            "Worker": {
                "replicas": 2,
                "template": {
                    "spec": {
                        "containers": [
                            {"name": "tensorflow", "image": "train:v1"}
                        ]
                    }
                },
            }
        }
    },
}


def _cli_and_cluster():
    return Cli(FakeCluster())


def _invoke(cli, argv):
    return run(make_parser().parse_args(argv), cli)


def test_resolve_kind_accepts_kind_and_plural():
    assert resolve_kind("tfjob") == "TFJob"
    assert resolve_kind("TFJobs") == "TFJob"
    assert resolve_kind("tpujobs") == "TPUJob"
    with pytest.raises(SystemExit):
        resolve_kind("nope")


def test_submit_get_list_delete(tmp_path, capsys):
    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    assert _invoke(cli, ["submit", str(path)]) == 0
    assert "tfjob.kubeflow.org/mnist created" in capsys.readouterr().out

    assert _invoke(cli, ["get", "tfjob", "mnist", "-o", "json"]) == 0
    job = json.loads(capsys.readouterr().out)
    assert job["metadata"]["name"] == "mnist"

    assert _invoke(cli, ["list", "tfjob"]) == 0
    out = capsys.readouterr().out
    assert "mnist" in out and "NAME" in out

    assert _invoke(cli, ["delete", "tfjob", "mnist"]) == 0
    with pytest.raises(NotFoundError):
        cli.cluster.get("TFJob", "default", "mnist")


def test_pods_and_logs_after_reconcile(tmp_path, capsys):
    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    _invoke(cli, ["submit", str(path)])
    capsys.readouterr()

    engine = make_engine("TFJob", cli.cluster)
    from tf_operator_tpu.api import tensorflow as tfapi

    job = tfapi.TFJob.from_dict(cli.cluster.get("TFJob", "default", "mnist"))
    engine.reconcile(job)

    assert _invoke(cli, ["pods", "tfjob", "mnist"]) == 0
    out = capsys.readouterr().out.splitlines()
    assert out == ["mnist-worker-0", "mnist-worker-1"]

    cli.cluster.append_pod_log("default", "mnist-worker-0", "step 1 loss 2.3")
    assert _invoke(cli, ["logs", "tfjob", "mnist", "--replica-type",
                         "Worker", "--index", "0"]) == 0
    out = capsys.readouterr().out
    assert "==> mnist-worker-0 <==" in out and "step 1 loss 2.3" in out


def test_wait_returns_by_terminal_state(tmp_path, capsys):
    from tf_operator_tpu.api import common

    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    _invoke(cli, ["submit", str(path)])

    job = cli.cluster.get("TFJob", "default", "mnist")
    job.setdefault("status", {})["conditions"] = [
        {"type": common.JOB_SUCCEEDED, "status": "True"}
    ]
    cli.cluster.update("TFJob", job)
    assert _invoke(cli, ["wait", "tfjob", "mnist", "--timeout", "5"]) == 0
    assert "Succeeded" in capsys.readouterr().out

    # a failed job exits 2, a timeout exits 1
    job = cli.cluster.get("TFJob", "default", "mnist")
    job["status"]["conditions"] = [
        {"type": common.JOB_FAILED, "status": "True"}
    ]
    cli.cluster.update("TFJob", job)
    assert _invoke(cli, ["wait", "tfjob", "mnist", "--timeout", "5"]) == 2


def test_submit_from_stdin(monkeypatch, capsys):
    import io

    cli = _cli_and_cluster()
    monkeypatch.setattr("sys.stdin", io.StringIO(yaml.safe_dump(TFJOB)))
    assert _invoke(cli, ["submit", "-"]) == 0
    assert "created" in capsys.readouterr().out
    assert cli.cluster.get("TFJob", "default", "mnist")


def test_global_flags_after_verb():
    """kubectl-style flag placement: -n/--kubeconfig parse after the verb."""
    args = make_parser().parse_args(["get", "tfjob", "mnist", "-n", "prod"])
    assert args.namespace == "prod"
    args = make_parser().parse_args(["-n", "pre", "get", "tfjob", "m"])
    assert args.namespace == "pre"
    args = make_parser().parse_args(
        ["submit", "job.yaml", "--kubeconfig", "/tmp/kc"])
    assert args.kubeconfig == "/tmp/kc"


def test_suspend_resume_verbs(tmp_path, capsys):
    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    assert _invoke(cli, ["submit", str(path)]) == 0
    engine = make_engine("TFJob", cli.cluster)

    def sync():
        from tf_operator_tpu.api import tensorflow as tfapi

        engine.reconcile(tfapi.TFJob.from_dict(
            cli.cluster.get("TFJob", "default", "mnist")))

    sync()
    assert len(cli.cluster.list_pods()) == 2

    assert _invoke(cli, ["suspend", "tfjob", "mnist"]) == 0
    assert "suspended" in capsys.readouterr().out
    sync()
    assert cli.cluster.list_pods() == []
    job = cli.cluster.get("TFJob", "default", "mnist")
    assert job["spec"]["runPolicy"]["suspend"] is True

    assert _invoke(cli, ["resume", "tfjob", "mnist"]) == 0
    assert "resumed" in capsys.readouterr().out
    sync()
    assert len(cli.cluster.list_pods()) == 2


def test_describe_shows_conditions_replicas_events(tmp_path, capsys):
    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    assert _invoke(cli, ["submit", str(path)]) == 0
    engine = make_engine("TFJob", cli.cluster)
    from tf_operator_tpu.api import tensorflow as tfapi

    engine.reconcile(tfapi.TFJob.from_dict(
        cli.cluster.get("TFJob", "default", "mnist")))
    for pod in cli.cluster.list_pods():
        pod["status"]["phase"] = "Running"
        cli.cluster.update_pod(pod)
    engine.reconcile(tfapi.TFJob.from_dict(
        cli.cluster.get("TFJob", "default", "mnist")))
    capsys.readouterr()

    assert _invoke(cli, ["describe", "tfjob", "mnist"]) == 0
    out = capsys.readouterr().out
    assert "Name:      mnist" in out
    assert "State:     Running" in out
    assert "Worker: active=2" in out
    assert "Running" in out and "Created" in out  # conditions table
    assert "mnist-worker-0" in out and "mnist-worker-1" in out
    assert "JobCreated" in out  # event vocabulary


def test_describe_events_include_age(tmp_path, capsys):
    """The Events section is a table with an AGE column computed from
    each event's timestamp — not just type/reason/message."""
    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    _invoke(cli, ["submit", str(path)])
    engine = make_engine("TFJob", cli.cluster)
    from tf_operator_tpu.api import tensorflow as tfapi

    engine.reconcile(tfapi.TFJob.from_dict(
        cli.cluster.get("TFJob", "default", "mnist")))
    capsys.readouterr()
    assert _invoke(cli, ["describe", "tfjob", "mnist"]) == 0
    out = capsys.readouterr().out
    assert "AGE" in out and "JobCreated" in out
    # a just-recorded event is seconds old ("JobCreated" also names a
    # condition reason — scope to the Events section)
    lines = out.splitlines()
    events_at = lines.index("Events:")
    event_line = next(l for l in lines[events_at:] if "JobCreated" in l)
    assert "<unknown>" not in event_line
    import re

    assert re.search(r"\b\d+s\b", event_line), event_line


def test_events_verb_lists_job_events(tmp_path, capsys):
    """`tpu-jobs events` — the kubectl-get-events analog over
    cluster.events_for: header + one aged row per recorded event."""
    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    _invoke(cli, ["submit", str(path)])
    engine = make_engine("TFJob", cli.cluster)
    from tf_operator_tpu.api import tensorflow as tfapi

    engine.reconcile(tfapi.TFJob.from_dict(
        cli.cluster.get("TFJob", "default", "mnist")))
    capsys.readouterr()
    assert _invoke(cli, ["events", "tfjob", "mnist"]) == 0
    out = capsys.readouterr().out
    assert "LAST SEEN" in out and "TYPE" in out and "REASON" in out
    assert "JobCreated" in out
    import re

    assert re.search(r"^\d+s\s+Normal", out.splitlines()[1]), out
    # no events yet for a fresh job -> friendly empty message, exit 0
    fresh = dict(TFJOB, metadata={"name": "quiet", "namespace": "default"})
    path.write_text(yaml.safe_dump(fresh))
    _invoke(cli, ["submit", str(path)])
    capsys.readouterr()
    assert _invoke(cli, ["events", "tfjob", "quiet"]) == 0
    assert "No events found." in capsys.readouterr().out
    # unknown job -> NotFound propagates (main() renders it cleanly)
    with pytest.raises(NotFoundError):
        cli.events("TFJob", "missing", "default")


def test_scale_verb_drives_replica_count(tmp_path, capsys):
    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    assert _invoke(cli, ["submit", str(path)]) == 0
    engine = make_engine("TFJob", cli.cluster)
    from tf_operator_tpu.api import tensorflow as tfapi

    def sync():
        engine.reconcile(tfapi.TFJob.from_dict(
            cli.cluster.get("TFJob", "default", "mnist")))

    sync()
    assert len(cli.cluster.list_pods()) == 2
    assert _invoke(cli, ["scale", "tfjob", "mnist", "--replicas", "4"]) == 0
    assert "scaled (Worker=4)" in capsys.readouterr().out
    sync()
    assert len(cli.cluster.list_pods()) == 4
    # unknown replica type is a clean error
    assert _invoke(cli, ["scale", "tfjob", "mnist", "--replicas", "1",
                         "--replica-type", "PS"]) == 1
    assert "no PS replicas" in capsys.readouterr().err


def test_scale_rejects_out_of_bounds_elastic(capsys):
    cli = _cli_and_cluster()
    cli.cluster.create("PyTorchJob", {
        "apiVersion": "kubeflow.org/v1", "kind": "PyTorchJob",
        "metadata": {"name": "el", "namespace": "default"},
        "spec": {
            "elasticPolicy": {"minReplicas": 1, "maxReplicas": 4},
            "pytorchReplicaSpecs": {"Worker": {
                "replicas": 2,
                "template": {"spec": {"containers": [
                    {"name": "pytorch", "image": "x"}]}}}}},
    })
    # overshoot would terminally fail the job at validation — reject here
    assert _invoke(cli, ["scale", "pytorchjob", "el", "--replicas", "6"]) == 1
    assert "outside elasticPolicy bounds" in capsys.readouterr().err
    assert _invoke(cli, ["scale", "pytorchjob", "el", "--replicas", "4"]) == 0
    doc = cli.cluster.get("PyTorchJob", "default", "el")
    assert doc["spec"]["pytorchReplicaSpecs"]["Worker"]["replicas"] == 4


def test_version_verb(capsys):
    cli = _cli_and_cluster()
    assert _invoke(cli, ["version"]) == 0
    assert "tpu-operator" in capsys.readouterr().out


def test_apply_creates_then_configures(tmp_path, capsys):
    """kubectl-apply idempotency: first apply creates, a second apply with
    a changed replica count deep-merge patches the stored job."""
    cli = _cli_and_cluster()
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(TFJOB))
    import copy as _copy

    assert _invoke(cli, ["apply", str(path)]) == 0
    assert "created" in capsys.readouterr().out
    # round-trip manifest: server-managed metadata (resourceVersion, uid)
    # in the applied doc is ignored, not merged into a conflict
    doc = _copy.deepcopy(cli.cluster.get("TFJob", "default", "mnist"))
    doc["spec"]["tfReplicaSpecs"]["Worker"]["replicas"] = 5
    doc.pop("status", None)
    path.write_text(yaml.safe_dump(doc))
    assert _invoke(cli, ["apply", str(path)]) == 0
    assert "configured" in capsys.readouterr().out
    stored = cli.cluster.get("TFJob", "default", "mnist")
    assert stored["spec"]["tfReplicaSpecs"]["Worker"]["replicas"] == 5
    # schema still enforced on the apply path
    bad = _copy.deepcopy(TFJOB)
    bad["spec"]["tfReplicaSpecs"]["Worker"]["restartPolicy"] = "Sometimes"
    path.write_text(yaml.safe_dump(bad))
    assert _invoke(cli, ["apply", str(path)]) == 1
    err = capsys.readouterr().err
    assert "restartPolicy" in err
