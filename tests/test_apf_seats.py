"""APF per-flow seat counts (ISSUE 11): one flow may not occupy every
execution seat, so a crash-looping worker process's relist barrage cannot
starve its sibling processes' flows.  Fast, tier-1: pure in-process
FairFlowController mechanics (the cross-process storms live in the slow
multi-process soak).
"""
import threading
import time

import pytest

from tf_operator_tpu.e2e.http_apiserver import FairFlowController, RejectedError
from tf_operator_tpu.engine import metrics


def test_flow_seat_cap_queues_even_with_global_seats_free():
    """A flow at its per-flow cap queues new arrivals although global
    seats are idle; releasing one of ITS seats dispatches the waiter."""
    apf = FairFlowController(seats=4, seats_per_flow=2, queue_timeout=5.0)
    apf.acquire("hog")
    apf.acquire("hog")
    assert metrics.APF_SEATS_IN_USE.get({"flow": "hog"}) == 2

    got = threading.Event()

    def third():
        apf.acquire("hog")  # must park: hog is at its 2-seat cap
        got.set()
        apf.release("hog")

    t = threading.Thread(target=third)
    t.start()
    assert not got.wait(0.15), "third hog acquire must queue at the cap"
    # 2 of 4 global seats are free the whole time
    apf.release("hog")
    assert got.wait(2.0), "freed flow seat must dispatch the hog waiter"
    t.join()
    apf.release("hog")
    assert metrics.APF_SEATS_IN_USE.get({"flow": "hog"}) == 0


def test_other_flows_dispatch_past_a_seat_capped_flow():
    """The round-robin dispatcher skips a flow parked at its seat cap —
    other flows' requests are admitted immediately instead of waiting
    behind it (the crash-looping-sibling isolation)."""
    apf = FairFlowController(seats=4, seats_per_flow=1, queue_timeout=5.0)
    apf.acquire("loop")  # the crash-looper occupies its one seat

    parked = threading.Event()

    def looper():
        apf.acquire("loop")  # parks at the cap
        parked.set()
        apf.release("loop")

    t = threading.Thread(target=looper)
    t.start()
    time.sleep(0.05)  # let the looper park so the ring is non-empty
    for _ in range(6):  # quiet flow sails through, repeatedly
        t0 = time.monotonic()
        apf.acquire("quiet")
        assert time.monotonic() - t0 < 0.5
        apf.release("quiet")
    assert not parked.is_set(), "capped flow must still be parked"
    apf.release("loop")
    assert parked.wait(2.0)
    t.join()
    apf.release("loop")


def test_flow_seat_cap_timeout_still_rejects():
    """A waiter parked solely by its flow's seat cap still honors the
    queue timeout — 429 with Retry-After, not an eternal park."""
    apf = FairFlowController(
        seats=4, seats_per_flow=1, queue_timeout=0.1, retry_after=0.5
    )
    apf.acquire("hog")
    with pytest.raises(RejectedError) as exc:
        apf.acquire("hog")
    assert exc.value.retry_after == 0.5
    apf.release("hog")


def test_no_cap_keeps_legacy_release_signature():
    """seats_per_flow=None (the default) is the pre-ISSUE-11 controller:
    release() without a flow stays valid and nothing is capped."""
    apf = FairFlowController(seats=2)
    apf.acquire("a")
    apf.acquire("a")  # 2 seats, one flow — allowed without a cap
    apf.release()
    apf.release()
