"""Serving telemetry (models/telemetry.py + serve_loop wiring): the
ServeStats aggregate must be internally consistent with the per-request
ServeResults, the new metric families must round-trip the Prometheus
text format, and the request lifecycle spans must export as valid,
well-nested Chrome trace JSON."""
import dataclasses
import json

import jax
import jax.numpy as jnp

from tf_operator_tpu.engine import metrics as em
from tf_operator_tpu.engine.tracing import Tracer
from tf_operator_tpu.models import llama
from tf_operator_tpu.models.serving import serve_loop
from tf_operator_tpu.models.telemetry import ServeStats, ServeTelemetry

from tests.test_metrics_exposition import parse_exposition


def _setup(seed=0, **cfg_kw):
    cfg_kw.setdefault("dtype", jnp.float32)
    cfg = llama.tiny(**cfg_kw)
    model = llama.Llama(cfg)
    params = model.init(jax.random.PRNGKey(seed),
                        jnp.zeros((1, 8), jnp.int32),
                        train=False)["params"]
    return cfg, model, params


def _prompts(cfg, lengths, seed=1):
    key = jax.random.PRNGKey(seed)
    out = []
    for n in lengths:
        key, k = jax.random.split(key)
        out.append(jax.random.randint(k, (n,), 0, cfg.vocab_size))
    return out


def _draft(cfg, seed=9):
    d_cfg = dataclasses.replace(cfg, n_layers=1)
    d_model = llama.Llama(d_cfg)
    d_params = d_model.init(jax.random.PRNGKey(seed),
                            jnp.zeros((1, 8), jnp.int32),
                            train=False)["params"]
    return d_model, d_params


# ------------------------------------------------------------ ServeStats
def test_serve_stats_plain_internally_consistent():
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6, 11, 3, 9, 7])
    res, stats = serve_loop(model, params, prompts, slots=2,
                            max_new_tokens=10, return_stats=True)
    assert isinstance(stats, ServeStats)
    assert stats.requests == len(prompts)
    assert stats.slots == 2 and not stats.speculative
    assert stats.total_tokens == sum(len(r.tokens) for r in res)
    assert stats.wall_time_s > 0
    assert stats.tokens_per_sec > 0
    # per-request physics: queued <= admitted <= first token <= finished
    assert len(stats.per_request) == len(prompts)
    for pr, r in zip(stats.per_request, res):
        assert pr["tokens"] == len(r.tokens)
        assert pr["slot"] == r.slot
        assert pr["queue_wait_s"] >= 0
        assert 0 <= pr["ttft_s"] <= pr["e2e_latency_s"]
        assert pr["queue_wait_s"] + pr["ttft_s"] <= pr["e2e_latency_s"]
        assert pr["e2e_latency_s"] <= stats.wall_time_s
        assert pr["accepted_drafts"] == 0 and pr["proposed_drafts"] == 0
    # aggregates match the per-request rows
    e2es = [pr["e2e_latency_s"] for pr in stats.per_request]
    assert abs(stats.e2e_latency_mean_s - sum(e2es) / len(e2es)) < 1e-9
    assert stats.e2e_latency_max_s == max(e2es)
    assert stats.ttft_max_s == max(pr["ttft_s"] for pr in stats.per_request)
    # occupancy bounded by the lane count and strictly positive (five
    # 10-token requests through 2 lanes certainly decoded)
    assert 0 < stats.occupancy_mean <= 2
    assert 1 <= stats.occupancy_max <= 2
    assert stats.decode_time_s > 0 and stats.prefill_time_s > 0
    # plain serving never speculates
    assert stats.accepted_drafts == 0 and stats.proposed_drafts == 0
    assert stats.acceptance_rate is None
    # CPU backend exposes no memory_stats — the profiler contract
    assert stats.hbm_peak_bytes == {}


def test_serve_stats_speculative_acceptance_matches_results():
    cfg, model, params = _setup(max_len=256)
    d_model, d_params = _draft(cfg)
    prompts = _prompts(cfg, [6, 9, 4])
    res, stats = serve_loop(model, params, prompts, slots=2,
                            max_new_tokens=10, draft=d_model,
                            draft_params=d_params, spec_k=3,
                            steps_per_sync=2, return_stats=True)
    assert stats.speculative
    assert stats.accepted_drafts == sum(r.accepted_drafts for r in res)
    assert stats.proposed_drafts == sum(r.proposed_drafts for r in res)
    assert stats.proposed_drafts > 0
    assert stats.acceptance_rate == (
        stats.accepted_drafts / stats.proposed_drafts)
    for pr, r in zip(stats.per_request, res):
        assert pr["accepted_drafts"] == r.accepted_drafts
        assert pr["proposed_drafts"] == r.proposed_drafts


def test_stats_collection_does_not_change_tokens():
    """Telemetry is measurement, not scheduling: tokens with and without
    return_stats (and with a private telemetry object) are identical."""
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6, 8, 5], seed=3)
    plain = serve_loop(model, params, prompts, slots=2, max_new_tokens=8)
    with_stats, _ = serve_loop(model, params, prompts, slots=2,
                               max_new_tokens=8, return_stats=True)
    private = serve_loop(model, params, prompts, slots=2,
                         max_new_tokens=8,
                         telemetry=ServeTelemetry(tracer=Tracer()))
    assert [r.tokens for r in plain] == [r.tokens for r in with_stats]
    assert [r.tokens for r in plain] == [r.tokens for r in private]


def test_empty_request_list_returns_empty_stats():
    cfg, model, params = _setup(max_len=64)
    res, stats = serve_loop(model, params, [], slots=3,
                            return_stats=True)
    assert res == []
    assert stats.requests == 0 and stats.total_tokens == 0
    # the CONFIGURED lane count is reported, not a phantom 0 — callers
    # normalize occupancy by stats.slots
    assert stats.slots == 3 and not stats.speculative
    assert serve_loop(model, params, []) == []


def test_summary_is_json_safe_and_drops_per_request():
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [5, 7], seed=5)
    _, stats = serve_loop(model, params, prompts, slots=2,
                          max_new_tokens=6, return_stats=True)
    s = stats.summary()
    assert "per_request" not in s
    json.dumps(s)  # round floats, ints, None, dicts only
    assert s["requests"] == 2


# ----------------------------------------------------------- exposition
def test_new_families_round_trip_exposition():
    cfg, model, params = _setup(max_len=128)
    prompts = _prompts(cfg, [6, 9], seed=7)
    before = em.SERVING_REQUESTS.get()
    tokens_before = em.SERVING_TOKENS.get()
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=8)
    samples = parse_exposition(em.expose_all())
    # counters advanced by exactly this run's contribution
    (_, req_count), = samples["tpu_operator_serving_requests_total"]
    assert req_count == before + len(prompts)
    (_, tok_count), = samples["tpu_operator_serving_tokens_total"]
    assert tok_count == tokens_before + sum(len(r.tokens) for r in res)
    # histograms expose buckets/sum/count and saw >= one obs per request
    for fam in ("tpu_operator_serving_ttft_seconds",
                "tpu_operator_serving_queue_wait_seconds",
                "tpu_operator_serving_request_latency_seconds"):
        assert f"{fam}_bucket" in samples, fam
        (_, count), = samples[f"{fam}_count"]
        assert count >= len(prompts)
    # the loop ended: the occupancy gauge idles at 0 (a scrape between
    # runs must not read the final block's lane count)
    (_, occ), = samples["tpu_operator_serving_batch_occupancy"]
    assert occ == 0
    assert em.SERVING_BATCH_OCCUPANCY.get() == 0


def test_speculative_generate_feeds_acceptance_family():
    from tf_operator_tpu.models.speculative import speculative_generate

    cfg, model, params = _setup(max_len=128)
    labels = {"path": "speculative_generate"}
    before = em.SERVING_PROPOSED_DRAFTS.get(labels)
    prompt = jnp.stack(_prompts(cfg, [8], seed=11))
    _, stats = speculative_generate(model, params, model, params,
                                    prompt, 12, k=3, return_stats=True)
    assert em.SERVING_PROPOSED_DRAFTS.get(labels) == (
        before + stats["proposed_drafts"])
    assert em.SERVING_ACCEPTED_DRAFTS.get(
        labels) >= stats["accepted_drafts"]


# ----------------------------------------------------------- trace spans
def test_chrome_trace_dump_valid_and_well_nested(tmp_path):
    cfg, model, params = _setup(max_len=256)
    tracer = Tracer()
    prompts = _prompts(cfg, [40, 6, 9], seed=9)
    res = serve_loop(model, params, prompts, slots=2, max_new_tokens=8,
                     prefill_chunk=8, prefill_chunks_per_sync=1,
                     telemetry=ServeTelemetry(tracer=tracer))
    # the span TREE: one root per request with the lifecycle children
    roots = tracer.traces()
    assert len(roots) == len(prompts)
    by_req = {sp.attrs["request"]: sp for sp in roots}
    for i, r in enumerate(res):
        root = by_req[i]
        assert root.name == "serve_request"
        assert root.category == "serving"
        assert root.attrs["slot"] == r.slot
        assert root.attrs["tokens"] == len(r.tokens)
        names = [c.name for c in root.children]
        assert names == ["queued", "prefill", "decode"]
        prefill = root.children[1]
        # the 40-token prompt streamed in 8-token segments
        if i == 0:
            assert len(prefill.children) == 5
            seg = prefill.children[0]
            assert seg.name == "prefill_segment"
            assert seg.attrs["token_start"] == 0
        # well-nested: every child interval inside its parent's
        for parent in root.walk():
            p_end = parent.wall_start + parent.duration
            for c in parent.children:
                assert c.wall_start >= parent.wall_start - 1e-6
                assert c.wall_start + c.duration <= p_end + 1e-6
    # the dump is valid trace-event JSON with the serving category
    path = tmp_path / "serve_trace.json"
    tracer.dump(str(path))
    doc = json.loads(path.read_text())
    events = doc["traceEvents"]
    assert all(e["ph"] == "X" and e["cat"] == "serving" for e in events)
    assert sum(1 for e in events if e["name"] == "serve_request") == 3
    for e in events:
        assert e["dur"] >= 0 and isinstance(e["ts"], float)


def test_record_rejects_unfinished_root():
    import pytest

    from tf_operator_tpu.engine.tracing import Span

    t = Tracer()
    with pytest.raises(ValueError, match="unfinished"):
        t.record(Span(name="x", start=0.0, wall_start=0.0))
