"""Local executor (runtime/local.py): real subprocesses driven by the real
operator stack — the hermetic analogue of the reference's cluster e2e tier
(SURVEY.md §4.4), with actual OS processes instead of containers."""
import sys
import textwrap

import pytest

from tf_operator_tpu.runtime.local import localize_env_value, run_local


def _job(kind, replica_key, rtypes, container, script, *, extra_spec=None,
         restart_policy=None, name="local"):
    specs = {}
    for rtype, n in rtypes.items():
        rspec = {
            "replicas": n,
            "template": {"spec": {"containers": [{
                "name": container,
                "image": "local",
                "command": ["python", "-c", textwrap.dedent(script)],
            }]}},
        }
        if restart_policy:
            rspec["restartPolicy"] = restart_policy
        specs[rtype] = rspec
    spec = {replica_key: specs}
    spec.update(extra_spec or {})
    return {
        "apiVersion": "kubeflow.org/v1",
        "kind": kind,
        "metadata": {"name": name, "namespace": "default"},
        "spec": spec,
    }


def test_localize_env_value():
    assert localize_env_value("j-worker-0.ns.svc:2222") == "127.0.0.1:2222"
    assert localize_env_value(
        "j-ps-0.ns.svc.cluster.local:1234") == "127.0.0.1:1234"
    cfg = '{"worker": ["a-worker-0.default.svc:2222", "a-worker-1.default.svc:2222"]}'
    assert localize_env_value(cfg) == \
        '{"worker": ["127.0.0.1:2222", "127.0.0.1:2222"]}'
    assert localize_env_value("plain-value") == "plain-value"


def test_tfjob_runs_to_succeeded_with_env_contract():
    """2 workers actually execute, see a well-formed TF_CONFIG, and the job
    goes Succeeded; logs carry each replica's own task index."""
    script = """
        import json, os
        cfg = json.loads(os.environ["TF_CONFIG"])
        assert cfg["task"]["type"] == "worker"
        assert len(cfg["cluster"]["worker"]) == 2
        assert cfg["cluster"]["worker"][0].startswith("127.0.0.1:")
        print("task-index", cfg["task"]["index"])
    """
    result = run_local(
        _job("TFJob", "tfReplicaSpecs", {"Worker": 2}, "tensorflow", script),
        timeout=90,
    )
    assert result["state"] == "Succeeded", result["logs"]
    combined = "\n".join(result["logs"].values())
    assert "task-index 0" in combined and "task-index 1" in combined


def test_tpujob_env_and_failure_path():
    """A TPUJob host that exits 1 permanently fails the job (ExitCode
    policy: 1 is non-retryable); env carries the TPU slice contract."""
    script = """
        import os, sys
        assert os.environ["TPU_WORKER_ID"] == "0"
        assert os.environ["COORDINATOR_ADDRESS"].startswith("127.0.0.1:")
        print("slice env ok"); sys.exit(1)
    """
    job = _job("TPUJob", "tpuReplicaSpecs", {"Worker": 1}, "tpu", script,
               extra_spec={"acceleratorType": "v5e-4"})
    result = run_local(job, timeout=90)
    assert result["state"] == "Failed", result["logs"]
    assert "slice env ok" in "\n".join(result["logs"].values())


def test_onfailure_restarts_until_success(tmp_path):
    """restartPolicy OnFailure: first run exits 1, the kubelet restarts the
    container in place, second run succeeds -> job Succeeded."""
    marker = tmp_path / "ran-once"
    script = f"""
        import os, sys
        marker = {str(marker)!r}
        if not os.path.exists(marker):
            open(marker, "w").close()
            print("first attempt fails"); sys.exit(1)
        print("second attempt succeeds")
    """
    job = _job("PyTorchJob", "pytorchReplicaSpecs", {"Master": 1}, "pytorch",
               script, restart_policy="OnFailure")
    result = run_local(job, timeout=90)
    assert result["state"] == "Succeeded", result["logs"]
    combined = "\n".join(result["logs"].values())
    assert "first attempt fails" in combined
    assert "restarting container (count 1)" in combined
    assert "second attempt succeeds" in combined


def test_missing_command_fails_cleanly():
    job = _job("TFJob", "tfReplicaSpecs", {"Worker": 1}, "tensorflow", "")
    job["spec"]["tfReplicaSpecs"]["Worker"]["template"]["spec"][
        "containers"][0].pop("command")
    result = run_local(job, timeout=60)
    assert result["state"] == "Failed", result["logs"]
    assert "no command" in "\n".join(result["logs"].values())


def test_cli_run_local(tmp_path, capsys):
    import yaml

    from tf_operator_tpu.sdk.cli import main

    job = _job("TFJob", "tfReplicaSpecs", {"Worker": 1}, "tensorflow",
               "print('hello from local pod')")
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(job))
    rc = main(["run-local", str(path), "--timeout", "90"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "tfjob/local: Succeeded" in out
    assert "hello from local pod" in out


def test_run_local_ignores_stale_kubeconfig(tmp_path, capsys, monkeypatch):
    """run-local must not construct a cluster backend: a stale KUBECONFIG
    cannot break the offline dev loop."""
    import yaml

    from tf_operator_tpu.sdk.cli import main

    monkeypatch.setenv("KUBECONFIG", "/nonexistent/kubeconfig")
    job = _job("TFJob", "tfReplicaSpecs", {"Worker": 1}, "tensorflow",
               "print('offline ok')")
    path = tmp_path / "job.yaml"
    path.write_text(yaml.safe_dump(job))
    rc = main(["run-local", str(path), "--timeout", "90"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "offline ok" in out


def test_run_local_timeout_is_reported():
    job = _job("TFJob", "tfReplicaSpecs", {"Worker": 1}, "tensorflow",
               "import time; time.sleep(60)")
    result = run_local(job, timeout=3.0)
    assert result["state"] == "Timeout"
    assert result["timed_out"] is True


def test_localize_bare_service_names_with_job_name():
    """PyTorch's MASTER_ADDR / torchrun's PET_RDZV_ENDPOINT carry the BARE
    headless-service name; with the pod's job name the local executor
    rewrites those too (and comma rosters element-wise), leaving foreign
    hosts alone."""
    assert localize_env_value("torchrc-master-0", "torchrc") == "127.0.0.1"
    assert localize_env_value(
        "el-worker-0:29400", "el") == "127.0.0.1:29400"
    assert localize_env_value(
        "lgb-worker-0:9091,lgb-worker-1:9091", "lgb"
    ) == "127.0.0.1:9091,127.0.0.1:9091"
    # not this job's services: untouched
    assert localize_env_value("other-master-0", "torchrc") == "other-master-0"
    assert localize_env_value("plain-value", "torchrc") == "plain-value"
    # without a job name the bare form stays (DNS .svc form still rewrites)
    assert localize_env_value("torchrc-master-0") == "torchrc-master-0"
