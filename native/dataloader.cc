// Native prefetching record loader — the host-side input pipeline.
//
// The reference has no data path at all (training data is the user
// container's problem); on TPU the host input pipeline must keep the MXU
// fed across the PCIe/HBM boundary, so this framework ships one: a fixed-
// size-record binary format read by pread worker threads into a bounded
// ring of batch buffers, consumed zero-copy-into-numpy via ctypes
// (tf_operator_tpu/data/loader.py).
//
// File format (written by tf_operator_tpu.data.write_records):
//   8 bytes  magic "TPUREC01"
//   u64      record_size (bytes, little-endian)
//   u64      n_records
//   then n_records * record_size bytes of payload.
//
// Sharding: records are assigned round-robin to (shard_id of n_shards),
// the multi-host split (one shard per TPU VM host) — disjointness comes
// from this assignment alone.  Shuffle: per-epoch mt19937 permutation of
// the host's own shard, seeded by seed+epoch (std::shuffle's permutation
// is implementation-defined, so the order differs from the numpy fallback
// for the same seed; only within-shard order is affected).

#include "tpuoperator.h"

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <numeric>
#include <random>
#include <string>
#include <thread>
#include <vector>

#ifdef _WIN32
#error "POSIX only"
#endif
#include <fcntl.h>
#include <unistd.h>

namespace {

constexpr char kMagic[8] = {'T', 'P', 'U', 'R', 'E', 'C', '0', '1'};

struct RecordFile {
  int fd = -1;
  uint64_t record_size = 0;
  uint64_t n_records = 0;
  off_t payload_off = 0;
};

struct Batch {
  std::vector<uint8_t> data;
  bool filled = false;
};

struct Loader {
  std::vector<RecordFile> files;
  std::vector<std::pair<uint32_t, uint64_t>> index;  // (file, record) mine only
  uint64_t record_size = 0;
  int batch_size = 0;
  uint64_t seed = 0;
  int shard_id = 0;
  int n_shards = 1;
  bool shuffle = true;
  bool loop_forever = true;

  // ring of batch buffers
  std::vector<Batch> ring;
  size_t head = 0, tail = 0, count = 0;  // filled-batch FIFO over ring slots
  std::mutex mu;
  std::condition_variable cv_producer, cv_consumer;
  bool stop = false;       // hard stop: error or destruction
  bool exhausted = false;  // soft stop: non-looping data ran out
  std::string error;

  std::vector<std::thread> workers;
  // producer cursor state (guarded by mu)
  std::vector<uint64_t> order;
  uint64_t cursor = 0;
  uint64_t epoch = 0;
  std::atomic<uint64_t> batches_produced{0};

  ~Loader() {
    {
      std::lock_guard<std::mutex> lk(mu);
      stop = true;
    }
    cv_producer.notify_all();
    cv_consumer.notify_all();
    for (auto& t : workers) t.join();
    for (auto& f : files)
      if (f.fd >= 0) close(f.fd);
  }

  void reshuffle_locked() {
    order.resize(index.size());
    std::iota(order.begin(), order.end(), 0);
    if (shuffle) {
      std::mt19937_64 rng(seed + epoch);
      std::shuffle(order.begin(), order.end(), rng);
    }
    cursor = 0;
  }

  // claim the next batch worth of record ids; returns false at end-of-data
  bool claim_locked(std::vector<uint64_t>& ids) {
    // dl_new rejects 0 < index < batch_size, but keep the guard local too:
    // a shard smaller than one batch can never produce (no within-batch
    // repeats), looping or not
    if (index.size() < static_cast<size_t>(batch_size)) return false;
    if (cursor + batch_size > order.size()) {  // drop remainder
      if (!loop_forever) return false;
      epoch++;
      reshuffle_locked();
    }
    ids.assign(order.begin() + cursor, order.begin() + cursor + batch_size);
    cursor += batch_size;
    return true;
  }

  void worker() {
    std::vector<uint64_t> ids;
    for (;;) {
      size_t slot;
      {
        std::unique_lock<std::mutex> lk(mu);
        cv_producer.wait(lk,
                         [&] { return stop || exhausted || count < ring.size(); });
        if (stop || exhausted) return;
        if (!claim_locked(ids)) {
          // soft drain: peers may still be filling reserved slots — the
          // consumer keeps reading until count hits 0, losing nothing
          exhausted = true;
          cv_producer.notify_all();
          cv_consumer.notify_all();
          return;
        }
        slot = tail;
        tail = (tail + 1) % ring.size();
        count++;  // reserve slot; consumer waits on `filled`
      }
      Batch& b = ring[slot];
      uint8_t* dst = b.data.data();
      for (int i = 0; i < batch_size; i++) {
        const auto& [fi, rec] = index[ids[i]];
        const RecordFile& f = files[fi];
        off_t off = f.payload_off + static_cast<off_t>(rec * record_size);
        size_t want = record_size;
        uint8_t* p = dst + i * record_size;
        while (want > 0) {
          ssize_t n = pread(f.fd, p, want, off);
          if (n <= 0) {
            std::lock_guard<std::mutex> lk(mu);
            error = "pread failed";
            stop = true;
            cv_consumer.notify_all();
            return;
          }
          want -= n;
          p += n;
          off += n;
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu);
        b.filled = true;
        batches_produced++;
      }
      cv_consumer.notify_all();
    }
  }
};

bool open_file(const char* path, RecordFile& out) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return false;
  char magic[8];
  uint64_t hdr[2];
  if (pread(fd, magic, 8, 0) != 8 || memcmp(magic, kMagic, 8) != 0 ||
      pread(fd, hdr, 16, 8) != 16) {
    close(fd);
    return false;
  }
  out.fd = fd;
  out.record_size = hdr[0];
  out.n_records = hdr[1];
  out.payload_off = 24;
  return true;
}

}  // namespace

extern "C" {

// paths: '\n'-separated record files. Returns nullptr on any open/header
// failure or record-size mismatch between files.
void* dl_new(const char* paths, int batch_size, int prefetch_depth,
             int n_threads, int shard_id, int n_shards, uint64_t seed,
             int shuffle, int loop_forever) {
  if (batch_size <= 0 || prefetch_depth <= 0 || n_threads <= 0 ||
      n_shards <= 0 || shard_id < 0 || shard_id >= n_shards)
    return nullptr;
  auto loader = std::make_unique<Loader>();
  std::string all(paths), item;
  size_t start = 0;
  while (start <= all.size()) {
    size_t nl = all.find('\n', start);
    item = all.substr(start, nl == std::string::npos ? nl : nl - start);
    start = nl == std::string::npos ? all.size() + 1 : nl + 1;
    if (item.empty()) continue;
    RecordFile f;
    if (!open_file(item.c_str(), f)) return nullptr;
    if (loader->record_size == 0) loader->record_size = f.record_size;
    if (f.record_size != loader->record_size) {
      close(f.fd);
      return nullptr;
    }
    loader->files.push_back(f);
  }
  if (loader->files.empty() || loader->record_size == 0) return nullptr;

  uint64_t global = 0;
  for (uint32_t fi = 0; fi < loader->files.size(); fi++)
    for (uint64_t r = 0; r < loader->files[fi].n_records; r++, global++)
      if (global % n_shards == static_cast<uint64_t>(shard_id))
        loader->index.push_back({fi, r});

  loader->batch_size = batch_size;
  loader->seed = seed;
  loader->shard_id = shard_id;
  loader->n_shards = n_shards;
  loader->shuffle = shuffle != 0;
  loader->loop_forever = loop_forever != 0;
  loader->ring.resize(prefetch_depth);
  for (auto& b : loader->ring)
    b.data.resize(static_cast<size_t>(batch_size) * loader->record_size);
  loader->reshuffle_locked();
  if (loader->index.size() < static_cast<size_t>(batch_size) &&
      !loader->index.empty())
    return nullptr;  // can never produce a full batch (even looping:
                     // a batch never repeats a record within itself)
  for (int i = 0; i < n_threads; i++)
    loader->workers.emplace_back(&Loader::worker, loader.get());
  return loader.release();
}

void dl_free(void* h) { delete static_cast<Loader*>(h); }

uint64_t dl_record_size(void* h) {
  return static_cast<Loader*>(h)->record_size;
}

uint64_t dl_num_records(void* h) {
  return static_cast<Loader*>(h)->index.size();
}

uint64_t dl_batches_produced(void* h) {
  return static_cast<Loader*>(h)->batches_produced.load();
}

// Copy the next ready batch into out (batch_size * record_size bytes).
// Returns 1 on success, 0 on end-of-data/stopped, -1 on io error.
int dl_next(void* h, uint8_t* out, uint64_t out_len) {
  auto* l = static_cast<Loader*>(h);
  if (out_len < static_cast<uint64_t>(l->batch_size) * l->record_size)
    return -1;
  size_t slot;
  {
    std::unique_lock<std::mutex> lk(l->mu);
    // a reserved slot (count>0, not yet filled) is always eventually filled
    // by its worker, so on soft exhaustion we only give up once count==0 —
    // no in-flight tail batch is ever dropped
    l->cv_consumer.wait(lk, [&] {
      return (l->count > 0 && l->ring[l->head].filled) || l->stop ||
             (l->exhausted && l->count == 0);
    });
    if (!(l->count > 0 && l->ring[l->head].filled))
      return l->error.empty() ? 0 : -1;
    slot = l->head;
  }
  std::memcpy(out, l->ring[slot].data.data(),
              static_cast<size_t>(l->batch_size) * l->record_size);
  {
    std::lock_guard<std::mutex> lk(l->mu);
    l->ring[slot].filled = false;
    l->head = (l->head + 1) % l->ring.size();
    l->count--;
  }
  l->cv_producer.notify_one();
  return 1;
}

}  // extern "C"
