// C ABI for the native operator runtime (loaded via ctypes from
// tf_operator_tpu/native/__init__.py).
#ifndef TPUOPERATOR_H_
#define TPUOPERATOR_H_

#include <cstdint>

extern "C" {

// ---- work queue (workqueue.cc) ----
void* wq_new(double base_delay_ms, double max_delay_ms);
void wq_free(void* h);
void wq_add(void* h, const char* key);
void wq_add_after(void* h, const char* key, double delay_ms);
double wq_add_rate_limited(void* h, const char* key);
int wq_get(void* h, double timeout_ms, char* buf, int buflen);
void wq_done(void* h, const char* key);
void wq_forget(void* h, const char* key);
int wq_num_requeues(void* h, const char* key);
int wq_len(void* h);
int wq_pending_delayed(void* h);
int wq_empty(void* h);
void wq_shutdown(void* h);

// ---- expectations (expectations.cc) ----
void* exp_new(double ttl_ms);
void exp_free(void* h);
void exp_set(void* h, const char* key, long long add, long long del);
void exp_raise(void* h, const char* key, long long add, long long del);
void exp_lower(void* h, const char* key, long long add, long long del);
int exp_satisfied(void* h, const char* key);
void exp_delete(void* h, const char* key);
int exp_count(void* h);

// ---- data loader (dataloader.cc) ----
void* dl_new(const char* paths, int batch_size, int prefetch_depth,
             int n_threads, int shard_id, int n_shards, uint64_t seed,
             int shuffle, int loop_forever);
void dl_free(void* h);
uint64_t dl_record_size(void* h);
uint64_t dl_num_records(void* h);
uint64_t dl_batches_produced(void* h);
int dl_next(void* h, uint8_t* out, uint64_t out_len);

}  // extern "C"

#endif  // TPUOPERATOR_H_
