// Native ControllerExpectations — double-creation protection counters.
//
// Mirrors the Python ControllerExpectations (engine/expectations.py) and
// kubeflow/common's expectation package semantics: per-key (add, delete)
// counters with a TTL; satisfied when fulfilled, expired, or never set.

#include "tpuoperator.h"

#include <chrono>
#include <mutex>
#include <string>
#include <unordered_map>

namespace {

using Clock = std::chrono::steady_clock;

struct Expectation {
  long long add = 0;
  long long del = 0;
  Clock::time_point stamp;
};

struct Expectations {
  std::mutex mu;
  std::unordered_map<std::string, Expectation> store;
  double ttl_ms;
  explicit Expectations(double ttl) : ttl_ms(ttl) {}
};

}  // namespace

extern "C" {

void* exp_new(double ttl_ms) { return new Expectations(ttl_ms); }

void exp_free(void* h) { delete static_cast<Expectations*>(h); }

void exp_set(void* h, const char* key, long long add, long long del) {
  auto* e = static_cast<Expectations*>(h);
  std::lock_guard<std::mutex> lk(e->mu);
  e->store[key] = {add, del, Clock::now()};
}

void exp_raise(void* h, const char* key, long long add, long long del) {
  auto* e = static_cast<Expectations*>(h);
  std::lock_guard<std::mutex> lk(e->mu);
  auto it = e->store.find(key);
  if (it == e->store.end()) {
    e->store[key] = {add, del, Clock::now()};
  } else {
    it->second.add += add;
    it->second.del += del;
  }
}

void exp_lower(void* h, const char* key, long long add, long long del) {
  auto* e = static_cast<Expectations*>(h);
  std::lock_guard<std::mutex> lk(e->mu);
  auto it = e->store.find(key);
  if (it != e->store.end()) {
    it->second.add -= add;
    it->second.del -= del;
  }
}

// 1 = satisfied (fulfilled, expired, or never set), 0 = must wait.
int exp_satisfied(void* h, const char* key) {
  auto* e = static_cast<Expectations*>(h);
  std::lock_guard<std::mutex> lk(e->mu);
  auto it = e->store.find(key);
  if (it == e->store.end()) return 1;
  const Expectation& exp = it->second;
  if (exp.add <= 0 && exp.del <= 0) return 1;
  auto age =
      std::chrono::duration<double, std::milli>(Clock::now() - exp.stamp);
  return age.count() > e->ttl_ms ? 1 : 0;
}

void exp_delete(void* h, const char* key) {
  auto* e = static_cast<Expectations*>(h);
  std::lock_guard<std::mutex> lk(e->mu);
  e->store.erase(key);
}

int exp_count(void* h) {
  auto* e = static_cast<Expectations*>(h);
  std::lock_guard<std::mutex> lk(e->mu);
  return static_cast<int>(e->store.size());
}

}  // extern "C"
