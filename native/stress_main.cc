// Concurrency stress driver for the native runtime, built to run under
// ThreadSanitizer (hack/native_tsan.sh).  SURVEY.md §5.2: the reference's
// `make test` never passes -race; this harness races the C++ workqueue and
// expectations the way the live manager does (N producers enqueueing /
// rate-limiting / forgetting keys while M consumers drain, plus a
// shutdown-while-blocked exit) and exits nonzero on any detected race or
// invariant breach.
#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include "tpuoperator.h"

namespace {

constexpr int kProducers = 4;
constexpr int kConsumers = 4;
constexpr int kKeys = 32;
constexpr int kOpsPerProducer = 2000;

std::atomic<long long> processed{0};
std::atomic<bool> failed{false};
std::atomic<bool> shutting_down{false};

void producer(void* wq, void* exp, int id) {
  for (int i = 0; i < kOpsPerProducer; ++i) {
    std::string key = "job-" + std::to_string((id * 31 + i) % kKeys);
    switch (i % 5) {
      case 0: wq_add(wq, key.c_str()); break;
      case 1: wq_add_rate_limited(wq, key.c_str()); break;
      case 2: wq_add_after(wq, key.c_str(), 0.1); break;
      case 3: wq_forget(wq, key.c_str()); break;
      default: wq_add(wq, key.c_str()); break;
    }
    exp_raise(exp, key.c_str(), 1, 0);
    exp_lower(exp, key.c_str(), 1, 0);
    (void)exp_satisfied(exp, key.c_str());
    if (i % 64 == 0) exp_delete(exp, key.c_str());
  }
}

void consumer(void* wq) {
  char buf[256];
  while (true) {
    int n = wq_get(wq, 50.0, buf, sizeof(buf));
    if (n < 0) {
      // idle timeout is NOT exit: a rate-limited item may still be in the
      // delay heap (backoff cap == this timeout); only shutdown ends us
      if (shutting_down.load()) return;
      continue;
    }
    bool ok = static_cast<size_t>(n) == std::strlen(buf);
    if (!ok) {
      std::fprintf(stderr, "length/content mismatch: %d vs %zu\n", n,
                   std::strlen(buf));
      failed = true;
    }
    processed.fetch_add(1);
    wq_done(wq, buf);  // always: a key stuck in `processing` wedges drain
    if (!ok) return;
  }
}

}  // namespace

int main() {
  void* wq = wq_new(1.0, 50.0);
  void* exp = exp_new(30000.0);

  std::vector<std::thread> threads;
  threads.reserve(kProducers + kConsumers);
  for (int c = 0; c < kConsumers; ++c) threads.emplace_back(consumer, wq);
  for (int p = 0; p < kProducers; ++p)
    threads.emplace_back(producer, wq, exp, p);
  for (int p = 0; p < kProducers; ++p) threads[kConsumers + p].join();

  // drain with a deadline (dedup keeps `processed` well below the op
  // count, and a detected failure must reach the report, not hang), then
  // shut down while consumers may be blocked in wq_get — the exact
  // teardown path OperatorManager.stop() exercises
  auto deadline = std::chrono::steady_clock::now() + std::chrono::seconds(10);
  while (!wq_empty(wq) && !failed.load() &&
         std::chrono::steady_clock::now() < deadline) {
    std::this_thread::sleep_for(std::chrono::milliseconds(5));
  }
  shutting_down = true;
  wq_shutdown(wq);
  for (int c = 0; c < kConsumers; ++c) threads[c].join();

  wq_free(wq);
  exp_free(exp);

  if (failed.load() || processed.load() == 0) {
    std::fprintf(stderr, "stress failed: processed=%lld\n", processed.load());
    return 1;
  }
  std::printf("native stress ok: processed=%lld keys=%d threads=%d\n",
              processed.load(), kKeys, kProducers + kConsumers);
  return 0;
}
