// Native rate-limiting work queue — the operator's hot dispatch path.
//
// Same contract as client-go's workqueue (and the Python fallback in
// tf_operator_tpu/k8s/informer.py): dedup on add, at-most-one worker per
// item, dirty re-queue on done(), delayed adds via a min-heap serviced by
// the getters themselves (no timer thread), per-item exponential backoff.
//
// Exposed through a flat C ABI for ctypes (see native/tpuoperator.h).

#include "tpuoperator.h"

#include <algorithm>
#include <chrono>
#include <condition_variable>
#include <cstring>
#include <deque>
#include <mutex>
#include <queue>
#include <string>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

using Clock = std::chrono::steady_clock;

struct Delayed {
  Clock::time_point fire_at;
  uint64_t seq;
  std::string key;
  bool operator>(const Delayed& o) const {
    return fire_at != o.fire_at ? fire_at > o.fire_at : seq > o.seq;
  }
};

struct WorkQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<std::string> queue;
  std::unordered_set<std::string> dirty;
  std::unordered_set<std::string> processing;
  std::priority_queue<Delayed, std::vector<Delayed>, std::greater<Delayed>>
      heap;
  std::unordered_map<std::string, int> failures;
  uint64_t seq = 0;
  bool shutdown = false;
  double base_delay_ms;
  double max_delay_ms;

  WorkQueue(double base_ms, double max_ms)
      : base_delay_ms(base_ms), max_delay_ms(max_ms) {}

  // caller holds mu
  void add_locked(const std::string& key) {
    if (shutdown || dirty.count(key)) return;
    dirty.insert(key);
    if (processing.count(key)) return;  // re-queued by done()
    queue.push_back(key);
    cv.notify_one();
  }

  // caller holds mu; move due delayed items onto the live queue
  void drain_due_locked(Clock::time_point now) {
    while (!heap.empty() && heap.top().fire_at <= now) {
      std::string key = heap.top().key;
      heap.pop();
      add_locked(key);
    }
  }
};

int copy_out(const std::string& s, char* buf, int buflen) {
  if (buf == nullptr || buflen <= 0) return -2;
  if (s.size() > static_cast<size_t>(buflen) - 1) return -2;  // would truncate
  int n = static_cast<int>(s.size());
  std::memcpy(buf, s.data(), n);
  buf[n] = '\0';
  return n;
}

}  // namespace

extern "C" {

void* wq_new(double base_delay_ms, double max_delay_ms) {
  return new WorkQueue(base_delay_ms, max_delay_ms);
}

void wq_free(void* h) { delete static_cast<WorkQueue*>(h); }

void wq_add(void* h, const char* key) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->add_locked(key);
}

void wq_add_after(void* h, const char* key, double delay_ms) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  if (q->shutdown) return;
  if (delay_ms <= 0) {
    q->add_locked(key);
    return;
  }
  q->heap.push({Clock::now() + std::chrono::microseconds(
                    static_cast<int64_t>(delay_ms * 1000)),
                ++q->seq, key});
  q->cv.notify_all();  // wake a getter so it re-computes its wait deadline
}

double wq_add_rate_limited(void* h, const char* key) {
  auto* q = static_cast<WorkQueue*>(h);
  double delay_ms;
  {
    std::lock_guard<std::mutex> lk(q->mu);
    int n = q->failures[key]++;
    delay_ms = q->base_delay_ms;
    for (int i = 0; i < n && delay_ms < q->max_delay_ms; i++) delay_ms *= 2;
    delay_ms = std::min(delay_ms, q->max_delay_ms);
  }
  wq_add_after(h, key, delay_ms);
  return delay_ms;
}

// Blocks up to timeout_ms (-1 = forever). Returns key length written into
// buf, or -1 on timeout/shutdown-empty.
int wq_get(void* h, double timeout_ms, char* buf, int buflen) {
  auto* q = static_cast<WorkQueue*>(h);
  auto deadline = timeout_ms < 0
                      ? Clock::time_point::max()
                      : Clock::now() + std::chrono::microseconds(
                                           static_cast<int64_t>(timeout_ms * 1000));
  std::unique_lock<std::mutex> lk(q->mu);
  for (;;) {
    q->drain_due_locked(Clock::now());
    if (!q->queue.empty()) {
      // copy out BEFORE taking ownership; an oversized key is popped AND
      // DROPPED — left at the head it would be re-hit by every subsequent
      // get, permanently wedging the worker pool on one bad key
      int n = copy_out(q->queue.front(), buf, buflen);
      if (n < 0) {
        std::string bad = q->queue.front();
        q->queue.pop_front();
        q->dirty.erase(bad);
        q->failures.erase(bad);
        return n;
      }
      std::string key = q->queue.front();
      q->queue.pop_front();
      q->dirty.erase(key);
      q->processing.insert(key);
      return n;
    }
    if (q->shutdown) return -1;
    auto wake = deadline;
    if (!q->heap.empty()) wake = std::min(wake, q->heap.top().fire_at);
    if (wake == Clock::time_point::max()) {
      q->cv.wait(lk);
    } else {
      if (q->cv.wait_until(lk, wake) == std::cv_status::timeout &&
          Clock::now() >= deadline && deadline != Clock::time_point::max()) {
        // one last drain so a just-due delayed item isn't missed
        q->drain_due_locked(Clock::now());
        if (q->queue.empty()) return -1;
      }
    }
  }
}

void wq_done(void* h, const char* key) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->processing.erase(key);
  // invariant: a key dirty while processing is never also in the queue
  // (add_locked skips the push when processing), so no membership scan
  if (q->dirty.count(key)) {
    q->queue.push_back(key);
    q->cv.notify_one();
  }
}

void wq_forget(void* h, const char* key) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->failures.erase(key);
}

int wq_num_requeues(void* h, const char* key) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  auto it = q->failures.find(key);
  return it == q->failures.end() ? 0 : it->second;
}

int wq_len(void* h) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  // count due-but-undrained items too so len() can't transiently read 0
  // while a delayed item is already due
  q->drain_due_locked(Clock::now());
  return static_cast<int>(q->queue.size());
}

int wq_pending_delayed(void* h) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  return static_cast<int>(q->heap.size());
}

int wq_empty(void* h) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->drain_due_locked(Clock::now());
  return q->queue.empty() && q->processing.empty() ? 1 : 0;
}

void wq_shutdown(void* h) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lk(q->mu);
  q->shutdown = true;
  q->cv.notify_all();
}

}  // extern "C"
