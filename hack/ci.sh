#!/usr/bin/env bash
# Local CI pipeline — the runnable form of test/workflows/e2e-workflow.yaml
# (the reference drives the same stages through Argo+Prow: build -> lint ->
# unit -> e2e -> sdk, SURVEY §3.5). Every stage must pass.
#
# CI_STAGES selects stage groups (the Prow-style presubmit matrix,
# reference prow_config.yaml:6-57 — .github/workflows/ci.yaml fans these
# out as parallel jobs):
#   native  — build + TSAN concurrency stress
#   static  — lint, generated-artifact drift, overlay rendering
#   unit    — build + unit/controller/numerics tests
#   e2e     — build + e2e scenarios + examples/sdk smoke
#   dryrun  — graft entry compile + 8-device multichip dryrun
#   bench   — build + operator-bench smoke (tiny sizes; correctness of the
#             bench harness itself, not a perf measurement)
# Default: all groups, sequentially.
set -euo pipefail
cd "$(dirname "$0")/.."

RUN="${CI_STAGES:-all}"
want() { [[ "$RUN" == "all" || " $RUN " == *" $1 "* ]]; }
stage() { echo; echo "=== $1 ==="; }

if want native || want unit || want e2e || want bench; then
  stage "build: native runtime core"
  make native
fi

if want native; then
  stage "native: tsan concurrency stress (the -race the reference never runs)"
  bash hack/native_tsan.sh
fi

if want static; then
  stage "lint: python compile check"
  python -m compileall -q tf_operator_tpu hack examples tests

  stage "manifests: generated CRDs in sync"
  python hack/gen_crds.py --check
  python hack/gen_apidoc.py --check
  python hack/gen_openapi.py --check
  python hack/gen_models.py --check

  stage "manifests: overlays render (hermetic kustomize)"
  python hack/release.py render --overlay standalone > /dev/null
  python hack/release.py render --overlay kubeflow > /dev/null
  python hack/release.py render --overlay webhook > /dev/null
  python hack/release.py render --overlay kind-e2e > /dev/null
fi

if want unit; then
  stage "unit + controller + numerics"
  python -m pytest tests/ -q -x --ignore=tests/test_e2e.py \
      --ignore=tests/test_examples.py --ignore=tests/test_sdk.py \
      --ignore=tests/test_torch_e2e.py --ignore=tests/test_jax_dist_e2e.py
fi

if want e2e; then
  stage "e2e scenarios"
  python -m pytest tests/test_e2e.py -q -x

  stage "real-consumer env contract (torch gloo + jax.distributed)"
  python -m pytest tests/test_torch_e2e.py tests/test_jax_dist_e2e.py -q

  stage "examples smoke (sdk + ladder)"
  python -m pytest tests/test_examples.py tests/test_sdk.py -q -x
fi

if want dryrun; then
  stage "graft entry: single-chip compile + 8-device dryrun"
  XLA_FLAGS=--xla_force_host_platform_device_count=8 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
g.dryrun_multichip(8)
fn, args = g.entry()
jax.jit(fn)(*args)
print("graft entry ok")
EOF
fi

if want bench; then
  stage "bench smoke: operator benches at tiny sizes (both backends)"
  JAX_PLATFORMS=cpu python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
import bench
for be in ("fake", "rest"):
    r = bench.bench_operator_scale(n_jobs=10, backend=be)
    assert r["all_running"], r
    s = bench.bench_startup_latency(runs=1, backend=be)
    assert s["failed_runs"] == 0, s
    print(f"bench smoke [{be}] ok:",
          r["jobs_per_sec"], "jobs/s,", s["create_to_first_step_s"], "s to step")
d = bench.bench_data_loader(n_records=2000, batch=128)
assert "records_per_sec" in d.get("python", {}), d
print("loader smoke ok:", d["python"]["records_per_sec"], "rec/s (python)")
EOF
fi

echo
echo "CI PASSED"
