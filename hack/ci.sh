#!/usr/bin/env bash
# Local CI pipeline — the runnable form of test/workflows/e2e-workflow.yaml
# (the reference drives the same stages through Argo+Prow: build -> lint ->
# unit -> e2e -> sdk, SURVEY §3.5). Every stage must pass.
set -euo pipefail
cd "$(dirname "$0")/.."

stage() { echo; echo "=== $1 ==="; }

stage "build: native runtime core"
make native

stage "native: tsan concurrency stress (the -race the reference never runs)"
bash hack/native_tsan.sh

stage "lint: python compile check"
python -m compileall -q tf_operator_tpu hack examples tests

stage "manifests: generated CRDs in sync"
python hack/gen_crds.py --check
python hack/gen_apidoc.py --check
python hack/gen_openapi.py --check

stage "manifests: overlays render (hermetic kustomize)"
python hack/release.py render --overlay standalone > /dev/null
python hack/release.py render --overlay kubeflow > /dev/null
python hack/release.py render --overlay webhook > /dev/null

stage "unit + controller + numerics"
python -m pytest tests/ -q -x --ignore=tests/test_e2e.py \
    --ignore=tests/test_examples.py --ignore=tests/test_sdk.py

stage "e2e scenarios"
python -m pytest tests/test_e2e.py -q -x

stage "examples smoke (sdk + ladder)"
python -m pytest tests/test_examples.py tests/test_sdk.py -q -x

stage "graft entry: single-chip compile + 8-device dryrun"
XLA_FLAGS=--xla_force_host_platform_device_count=8 python - <<'EOF'
import jax
jax.config.update("jax_platforms", "cpu")
import __graft_entry__ as g
g.dryrun_multichip(8)
fn, args = g.entry()
jax.jit(fn)(*args)
print("graft entry ok")
EOF

echo
echo "CI PASSED"
