#!/usr/bin/env python
"""Release / deploy CLI — reference py/kubeflow/tf_operator/{release,deploy}.py.

  python hack/release.py release --registry gcr.io/me [--push] [--run]
  python hack/release.py render  --overlay standalone [--image reg/op:tag]
  python hack/release.py cluster --project p --zone z --name c \
      --tpu-pool v5e-16=4x4 [--run]
  python hack/release.py teardown --project p --zone z --name c [--run]

Everything is a dry-run printing the command plan unless --run is given.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tf_operator_tpu.deploy import cluster as cl  # noqa: E402
from tf_operator_tpu.deploy import release as rel  # noqa: E402
from tf_operator_tpu.deploy.render import render_overlay, to_yaml_stream  # noqa: E402
from tf_operator_tpu.deploy.runner import CommandRunner  # noqa: E402

REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    pr = sub.add_parser("release")
    pr.add_argument("--registry", required=True)
    pr.add_argument("--version", default="0.1.0")
    pr.add_argument("--push", action="store_true")
    pr.add_argument("--run", action="store_true")

    pv = sub.add_parser("render")
    pv.add_argument("--overlay", default="standalone",
                    choices=("standalone", "kubeflow", "webhook", "kind-e2e"))
    pv.add_argument("--image", default=None)

    pc = sub.add_parser("cluster")
    pc.add_argument("--project", required=True)
    pc.add_argument("--zone", required=True)
    pc.add_argument("--name", required=True)
    pc.add_argument("--tpu-pool", action="append", default=[],
                    help="acceleratorType[=topology], e.g. v5e-16=4x4")
    pc.add_argument("--run", action="store_true")

    pt = sub.add_parser("teardown")
    pt.add_argument("--project", required=True)
    pt.add_argument("--zone", required=True)
    pt.add_argument("--name", required=True)
    pt.add_argument("--run", action="store_true")

    args = p.parse_args(argv)

    if args.cmd == "render":
        print(to_yaml_stream(render_overlay(REPO_ROOT, args.overlay,
                                            image=args.image)))
        return 0

    runner = CommandRunner(dry_run=not getattr(args, "run", False), echo=True)
    if args.cmd == "release":
        cfg = rel.ReleaseConfig(repo_root=REPO_ROOT, registry=args.registry,
                                version=args.version)
        artifacts = rel.release(runner, cfg, push=args.push)
        print(json.dumps(artifacts, indent=2))
    elif args.cmd in ("cluster", "teardown"):
        pools = {}
        for spec in getattr(args, "tpu_pool", []) or []:
            acc, _, topo = spec.partition("=")
            pools[acc] = topo
        ccfg = cl.ClusterConfig(project=args.project, zone=args.zone,
                                name=args.name, tpu_pools=pools)
        if args.cmd == "cluster":
            cl.setup_cluster(runner, ccfg)
        else:
            cl.teardown_cluster(runner, ccfg)
    if runner.dry_run:
        print("# dry run — re-run with --run to execute", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
