#!/bin/bash
# Opportunistic TPU chip grabber: probe the shared device pool and, when a
# chip frees up, land real-TPU evidence in BENCH_TPU_LAST_GOOD.json —
# FIRST a micro bench (BENCH_MICRO=1: few steps, no sweeps, no T5/BERT
# compiles) so even a short window caches something, THEN the full bench.
# The cache is git-committed the moment it appears/changes so a later
# session crash cannot lose it.  Run under nohup for a whole session:
#   hack/tpu_grab.sh [interval_s] [probe_timeout_s] [bench_timeout_s]
#
# The benches run with BENCH_SKIP_PROBE=1: this loop's probe is the only
# pre-claim, so each bench's own jax init is the next (single) pool claim —
# the pool has been observed to wedge a claim that follows a rapid
# claim/release cycle, so fewer claims is strictly safer.  A hard `timeout`
# around each bench keeps a wedged claim from blocking the loop forever;
# the bench checkpoints the cache after every completed arm, so even a
# timeout kill keeps whatever measured.
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-300}"
PROBE_TIMEOUT="${2:-120}"
BENCH_TIMEOUT="${3:-5400}"
MICRO_TIMEOUT="${MICRO_TIMEOUT:-2400}"

commit_cache() {
  # commit only the cache file; racing the main session's commits is fine
  # (retry once after a short pause if the index is locked)
  # diff against HEAD (not the index): content staged by a failed earlier
  # attempt must still trigger a commit, not silently ride into the main
  # session's next unrelated commit
  if ! git diff --quiet HEAD -- BENCH_TPU_LAST_GOOD.json 2>/dev/null \
      || ! git ls-files --error-unmatch BENCH_TPU_LAST_GOOD.json >/dev/null 2>&1; then
    for _ in 1 2; do
      if git add BENCH_TPU_LAST_GOOD.json \
          && git commit -q -m "Record last-good TPU bench cache ($1)" \
               -- BENCH_TPU_LAST_GOOD.json; then
        echo "$(date -u +%FT%TZ) cache committed ($1)"
        return 0
      fi
      sleep 10
    done
    echo "$(date -u +%FT%TZ) cache commit failed ($1)"
  fi
}

while true; do
  if timeout "$PROBE_TIMEOUT" python -c \
      'import jax,sys; sys.exit(0 if jax.devices()[0].platform != "cpu" else 1)' \
      >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) probe OK - running micro bench"
    sleep 5   # let the probe's claim fully release before the bench claims
    BENCH_SKIP_PROBE=1 BENCH_MICRO=1 timeout "$MICRO_TIMEOUT" python bench.py \
      > /tmp/bench_grab_micro.json 2>/tmp/bench_grab_micro.err
    [ -f BENCH_TPU_LAST_GOOD.json ] && commit_cache micro
    echo "$(date -u +%FT%TZ) micro done - running full bench"
    sleep 30  # claim cool-down between the micro and full claims
    BENCH_SKIP_PROBE=1 timeout "$BENCH_TIMEOUT" python bench.py \
      > /tmp/bench_grab_last.json 2>/tmp/bench_grab_last.err
    [ -f BENCH_TPU_LAST_GOOD.json ] && commit_cache full
    if grep -q '"source": "live"' /tmp/bench_grab_last.json 2>/dev/null; then
      echo "$(date -u +%FT%TZ) live TPU bench captured -> BENCH_TPU_LAST_GOOD.json"
      exit 0
    fi
    echo "$(date -u +%FT%TZ) full bench ran but not live-TPU; retrying"
  else
    echo "$(date -u +%FT%TZ) pool busy"
  fi
  sleep "$INTERVAL"
done
