#!/bin/bash
# Opportunistic TPU chip grabber: probe the shared device pool and, when a
# chip frees up, run the full bench so BENCH_TPU_LAST_GOOD.json catches a
# real-TPU artifact even if the pool is busy again at round end (the cache
# is merged into later bench output with "source: cached" provenance).
# Run under tmux/nohup for a whole session:
#   hack/tpu_grab.sh [interval_s] [probe_timeout_s] [bench_timeout_s]
#
# The bench runs with BENCH_SKIP_PROBE=1: this loop's probe is the only
# pre-claim, so the bench's own jax init is the next (single) pool claim —
# the pool has been observed to wedge a claim that follows a rapid
# claim/release cycle, so fewer claims is strictly safer.  A hard `timeout`
# around the bench keeps a wedged claim from blocking the loop forever.
set -u
cd "$(dirname "$0")/.."
INTERVAL="${1:-300}"
PROBE_TIMEOUT="${2:-120}"
BENCH_TIMEOUT="${3:-5400}"
while true; do
  if timeout "$PROBE_TIMEOUT" python -c \
      'import jax,sys; sys.exit(0 if jax.devices()[0].platform != "cpu" else 1)' \
      >/dev/null 2>&1; then
    echo "$(date -u +%FT%TZ) probe OK - running bench"
    sleep 5   # let the probe's claim fully release before the bench claims
    BENCH_SKIP_PROBE=1 timeout "$BENCH_TIMEOUT" python bench.py \
      > /tmp/bench_grab_last.json 2>/tmp/bench_grab_last.err
    if grep -q '"source": "live"' /tmp/bench_grab_last.json 2>/dev/null; then
      echo "$(date -u +%FT%TZ) live TPU bench captured -> BENCH_TPU_LAST_GOOD.json"
      exit 0
    fi
    echo "$(date -u +%FT%TZ) bench ran but not live-TPU; retrying"
  else
    echo "$(date -u +%FT%TZ) pool busy"
  fi
  sleep "$INTERVAL"
done
