#!/usr/bin/env bash
# ThreadSanitizer run of the native runtime's concurrency stress driver
# (native/stress_main.cc).  SURVEY.md §5.2: the reference's `make test`
# never passes -race; this is the C++ analogue, run as a CI stage.
set -euo pipefail
cd "$(dirname "$0")/.."

out=$(mktemp -d)
trap 'rm -rf "$out"' EXIT

g++ -std=c++17 -O1 -g -fsanitize=thread -fno-omit-frame-pointer \
    -Inative \
    native/workqueue.cc native/expectations.cc native/stress_main.cc \
    -o "$out/native_stress" -lpthread

# halt_on_error: any data race fails CI loudly; the outer timeout bounds
# any unforeseen hang (TSan slows scheduling 5-20x)
TSAN_OPTIONS="halt_on_error=1" timeout 120 "$out/native_stress"
echo "native tsan stress: PASS"
