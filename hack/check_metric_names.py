#!/usr/bin/env python
"""Metric naming lint — `make metrics-lint` (run inside `make test`).

Imports the metric registry (engine/metrics.py — every family in the
codebase registers itself there at import) and enforces the Prometheus
naming conventions the docs and dashboards rely on:

  - every family carries the shared `tpu_operator_` prefix, so one
    scrape-config relabel and one Grafana variable cover the operator;
  - unit suffixes: Counters end in `_total` (the value is a running
    count); Histograms end in `_seconds`, `_bytes`, or `_ops` (the only
    units we record — a unitless histogram is a smell); Gauges never end in
    `_total` (a gauge that counts should be a Counter) and, when they
    measure a unit, name it (`_bytes`, `_seconds`);
  - non-empty HELP text (an undocumented family is unusable at 3am);
  - no duplicate family registration — two objects exposing the same
    name produce a duplicate `# TYPE` block, which strict parsers
    (promtool, OpenMetrics) reject for the whole target.

Exit 0 clean, 1 with one line per violation.
"""
from __future__ import annotations

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# Capacity gauges: `<unit>s_total` reading as "how many exist" (a level
# set once per run, not a monotonic count) is allowed for exactly these
# names — the paged-KV pool capacity, whose used/total ratio is the
# dashboards' block-occupancy formula.  Anything else ending in _total
# still fails: a gauge that COUNTS should be a Counter.
_CAPACITY_GAUGES = {"tpu_operator_serving_kv_blocks_total"}

# Families external consumers depend on BY NAME (docs/monitoring.md
# PromQL, SLO dashboards): renaming or dropping one silently breaks
# every recording rule built on it, so the lint pins name AND type.
# The per-job SLO families are derived by the flight recorder
# (engine/timeline.py) — the ISSUE 10 contract.
_REQUIRED_FAMILIES = {
    "tpu_operator_job_time_to_scheduled_seconds": "Histogram",
    "tpu_operator_job_time_to_running_seconds": "Histogram",
    "tpu_operator_job_restart_mttr_seconds": "Histogram",
    "tpu_operator_job_timeline_events_total": "Counter",
    "tpu_operator_job_timeline_evictions_total": "Counter",
    # elastic resize (ISSUE 12): resize_requested -> resumed per
    # transition, derived by the flight recorder like the families above
    "tpu_operator_job_resize_duration_seconds": "Histogram",
    # paged-attention kernel rollout (ISSUE 13): the pallas/gather
    # per-request split and the sliding-window eviction rate —
    # docs/monitoring.md's kernel-path-ratio and window-eviction PromQL
    # read these by name
    "tpu_operator_serving_paged_kernel_requests_total": "Counter",
    "tpu_operator_serving_kv_window_evicted_blocks_total": "Counter",
    # serving-fleet control plane (ISSUE 14): the router's dispatch
    # breakdown + queue depth and the autoscaler's fleet shape + scale
    # activity — docs/monitoring.md's occupancy-spread, scale-reaction,
    # and dispatch-reason PromQL read these by name
    "tpu_operator_serving_fleet_replicas": "Gauge",
    "tpu_operator_serving_router_dispatch_total": "Counter",
    "tpu_operator_serving_router_queue_depth": "Gauge",
    "tpu_operator_serving_fleet_scale_events_total": "Counter",
    # serving-fleet failure domain (ISSUE 15): the scrape transport's
    # success ratio + per-replica age, and the router's ejection /
    # degraded-fallback / hedging activity — docs/monitoring.md's
    # scrape-success, ejection-rate, and hedge-win-rate PromQL read
    # these by name
    "tpu_operator_serving_scrape_attempts_total": "Counter",
    "tpu_operator_serving_scrape_age_seconds": "Gauge",
    "tpu_operator_serving_replica_ejections_total": "Counter",
    "tpu_operator_serving_router_degraded_total": "Counter",
    "tpu_operator_serving_hedge_requests_total": "Counter",
    # request flight recorder + windowed SLO engine (ISSUE 16): the
    # per-axis multi-window burn rates and the recorder's own volume /
    # eviction counters — docs/monitoring.md's burn-rate alerting PromQL
    # reads these by name
    "tpu_operator_serving_slo_burn_rate": "Gauge",
    "tpu_operator_serving_slo_window_p99_seconds": "Gauge",
    "tpu_operator_serving_slo_burns_total": "Counter",
    "tpu_operator_serving_request_timeline_events_total": "Counter",
    "tpu_operator_serving_request_timeline_evictions_total": "Counter",
    # iteration-level scheduling (ISSUE 19): the continuous scheduler's
    # step-mix gauges and the wasted-lane-step counter —
    # docs/monitoring.md's fused-prefill-ratio and wasted-step-rate
    # PromQL read these by name
    "tpu_operator_serving_step_decode_rows": "Gauge",
    "tpu_operator_serving_step_prefill_tokens": "Gauge",
    "tpu_operator_serving_lane_wasted_steps_total": "Counter",
    # disaggregated prefill/decode serving (ISSUE 20): the KV-block
    # handoff's volume (phase=exported/elided/adopted/deduped), wire
    # latency (side=export/adopt), and decode-side admission retries —
    # docs/monitoring.md's handoff-dedup-ratio and retry-rate PromQL
    # read these by name
    "tpu_operator_serving_handoff_blocks_total": "Counter",
    "tpu_operator_serving_handoff_duration_seconds": "Histogram",
    "tpu_operator_serving_handoff_retries_total": "Counter",
}


def check_registry() -> list:
    from tf_operator_tpu.engine import metrics as em

    with em._LOCK:
        registry = list(em._REGISTRY)
    errors = []
    seen = {}
    for m in registry:
        where = f"{m.name} ({type(m).__name__})"
        if not m.name.startswith(em.PREFIX + "_"):
            errors.append(
                f"{where}: missing shared prefix {em.PREFIX!r}_")
        if not m.help.strip():
            errors.append(f"{where}: empty HELP text")
        if m.TYPE == "counter" and not m.name.endswith("_total"):
            errors.append(f"{where}: counters must end in _total")
        if m.TYPE == "histogram" and not m.name.endswith(
                ("_seconds", "_bytes", "_ops")):
            errors.append(
                f"{where}: histograms must end in _seconds, _bytes, or "
                f"_ops (the units this codebase records; _ops covers "
                f"count-valued distributions like fan-out batch sizes)")
        if m.TYPE == "gauge":
            if (m.name.endswith("_total")
                    and m.name not in _CAPACITY_GAUGES):
                errors.append(
                    f"{where}: a gauge must not end in _total — a "
                    f"monotonic count should be a Counter (capacity "
                    f"levels may be allowlisted in _CAPACITY_GAUGES)")
            # gauges may be unitless (occupancy, leader flag) but a
            # trailing pseudo-unit that is not a real unit is a typo
            for bad in ("_second", "_byte", "_secs", "_ms"):
                if m.name.endswith(bad):
                    errors.append(
                        f"{where}: suffix {bad!r} is not a canonical "
                        f"unit (use _seconds / _bytes)")
        if m.name in seen:
            errors.append(
                f"{where}: duplicate family registration (first "
                f"registered as {seen[m.name]})")
        else:
            seen[m.name] = type(m).__name__
    for name, want_type in sorted(_REQUIRED_FAMILIES.items()):
        got = seen.get(name)
        if got is None:
            errors.append(
                f"{name}: required family missing from the registry "
                f"(docs/monitoring.md PromQL depends on it by name)")
        elif got != want_type:
            errors.append(
                f"{name}: required family must be a {want_type}, "
                f"registered as {got}")
    return errors


def main() -> int:
    errors = check_registry()
    if errors:
        for e in errors:
            print(f"metrics-lint: {e}", file=sys.stderr)
        print(f"metrics-lint: {len(errors)} violation(s)", file=sys.stderr)
        return 1
    from tf_operator_tpu.engine import metrics as em

    with em._LOCK:
        n = len(em._REGISTRY)
    print(f"metrics-lint: {n} families clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
