# Developer entrypoints, kubebuilder-style (reference Makefile:39-86:
# manifests / generate / test / build / deploy).
IMG ?= kubeflow/tpu-training-operator:latest
CXX ?= g++
CXXFLAGS ?= -O2 -std=c++17 -fPIC -Wall -Wextra -pthread
NATIVE_DIR := native
NATIVE_LIB := tf_operator_tpu/native/libtpuoperator.so
NATIVE_SRCS := $(wildcard $(NATIVE_DIR)/*.cc)

.PHONY: all manifests verify-manifests test metrics-lint chaos bench bench-scale bench-startup bench-shard bench-multiproc bench-warmpool bench-sched bench-paged bench-serve-cb bench-paged-decode bench-timeline bench-elastic bench-fleet bench-fleet-chaos bench-reqtrace bench-cluster bench-disagg native clean docker-build deploy undeploy

all: native manifests

# Regenerate CRDs from the Python API types (reference `make manifests`).
manifests:
	python hack/gen_crds.py

verify-manifests:
	python hack/gen_crds.py --check

# Native runtime core (workqueue/expectations) as a shared library.
native: $(NATIVE_LIB)

$(NATIVE_LIB): $(NATIVE_SRCS) $(wildcard $(NATIVE_DIR)/*.h)
	mkdir -p $(dir $@)
	$(CXX) $(CXXFLAGS) -shared -o $@ $(NATIVE_SRCS)

# Static metric-naming conventions (shared prefix, unit suffixes, no
# duplicate family registration) over the whole registry.
metrics-lint:
	python hack/check_metric_names.py

# `make test` exercises the chaos harness on its default single seed (the
# soak in tests/test_chaos.py, which now includes the seeded
# shard-crash-mid-storm soak); `make chaos` widens it to several fixed
# seeds for the full fault-injection sweep (docs/robustness.md).
test: native metrics-lint
	python -m pytest tests/ -x -q

chaos:
	CHAOS_SEEDS="1337,4242,90210" python -m pytest tests/test_chaos.py -q

bench:
	python bench.py

# Operator control-plane throughput on BOTH backends (in-memory store and
# ClusterClient + REST façade), with the per-verb/kind API-request tally,
# cached-lister hit/miss, and the rest-phase breakdown — the ISSUE 4
# "zero steady-state LISTs" evidence, no TPU required.
bench-scale:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_operator_scale; \
	print(json.dumps({be: bench_operator_scale(backend=be) for be in ('fake', 'rest')}, indent=1))"

# N-replica gang startup latency (1/8/32 workers, fake + rest-over-real-
# socket), --control-fanout 1 vs 8 side by side, with the pooled
# transport's connections created/reused per run — the pooled keep-alive +
# slow-start fan-out evidence, no TPU required.
bench-startup:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_startup_replica_sweep; \
	print(json.dumps(bench_startup_replica_sweep(), indent=1))"

# Sharded control-plane throughput + failover: bench_operator_scale at
# shards 1/4/8 on fake + rest backends — jobs/s, reconcile p99, and (on
# sharded rows) crash-failover recovery time per row (ISSUE 6 evidence,
# no TPU required).
bench-shard:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_shard_sweep; \
	print(json.dumps(bench_shard_sweep(), indent=1))"

# Multi-process control plane: shards 1/4 as in-process workers vs real
# supervised worker PROCESSES over the same HTTP apiserver, with a
# kill -9 failover probe (takeover + end-to-end recovery time) and the
# watch-journal hit/cache ratios per multi-process row — the ISSUE 11
# GIL-escape evidence.  Rows land in BENCH_r10.json.
bench-multiproc:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_multiproc_sweep; \
	print(json.dumps(bench_multiproc_sweep(), indent=1))"

# Warm-pool cold-start sweep: create-to-first-running p50/p99 and
# warm-hit ratio with 0/30/120s simulated image-pull+init latency, warm
# pool off vs on, fake + rest backends (ISSUE 7 evidence, no TPU
# required).  Rows land in BENCH_r06.json.
bench-warmpool:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_cold_start; \
	print(json.dumps(bench_cold_start(), indent=1))"

# Paged-KV dense-vs-paged sweep on the tiny llama config: concurrent
# lanes at a fixed simulated HBM budget (>= 2x is the regression bound
# asserted in tests/test_bench_infra.py), token parity, shared-prefix
# admission TTFT (copy vs refcount), CoW + blocks-per-token per row
# (ISSUE 9 evidence, no TPU required).  Rows land in BENCH_r08.json.
bench-paged:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_paged; \
	print(json.dumps(bench_paged(), indent=1))"

# Slot loop vs token-level continuous batching at a FIXED block pool
# (ISSUE 19): same prefill-heavy heterogeneous-budget trace, same
# slots, same pool_blocks — only scheduler= differs.  Headlines:
# tokens/s ratio (>= 1.5x) and TTFT p99 (strictly better), with greedy
# token parity asserted in-bench.  Rows land in BENCH_r17.json;
# bounds pinned by tests/test_bench_infra.py.
bench-serve-cb:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_serve_cb; \
	print(json.dumps(bench_serve_cb(), indent=1))"

# Paged decode-step sweep: pallas block-indexed kernel vs table gather
# vs dense ring at 1/8/32 lanes x block_size 16/64 — per-step time,
# blocks-touched accounting, token parity, and a cache_sharding row
# asserting the paged decode block is a sharding fixpoint (zero
# per-step resharding transfers) on a tp=2 mesh (ISSUE 13 evidence;
# interpret-mode rows assert parity + blocks-touched, not wall-clock —
# regression bounds in tests/test_zpagedkernel.py).  Rows land in
# BENCH_r12.json.
bench-paged-decode:
	JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=2" \
	python -c "import json; from bench import bench_paged_decode; \
	print(json.dumps(bench_paged_decode(), indent=1))"

# Cluster-scheduler policy sweep: makespan + Jain fairness per
# bin-packing policy (spread / packed / throughput_ratio) on a mixed
# contended trace over a heterogeneous slice inventory, with preemption
# counts (ISSUE 8 evidence, no TPU required).  Rows land in BENCH_r07.json.
bench-sched:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_sched; \
	print(json.dumps(bench_sched(), indent=1))"

# Flight-recorder overhead pair: bench_operator_scale with the job
# timeline recorder off vs on (alternated repeats, best-of comparison) —
# the ISSUE 10 acceptance evidence that recording costs <= 5% reconcile
# throughput.  Rows land in BENCH_r09.json.
bench-timeline:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_timeline; \
	print(json.dumps(bench_timeline(), indent=1))"

# Elastic resize vs whole-gang eviction under capacity pressure: one
# deterministic SimClock trace (low-priority elastic gang squeezed by a
# high-priority arrival), scored on victim goodput fraction, wasted
# replica-seconds, restarts, and time-to-recover (ISSUE 12 evidence, no
# TPU required).  Rows land in BENCH_r11.json.
bench-elastic:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_elastic; \
	print(json.dumps(bench_elastic(), indent=1))"

# Serving-fleet control plane: >= 1k simulated concurrent users on a
# seeded diurnal/bursty trace with heavy-tailed prompts, served by one
# big static replica vs round-robin-over-a-fixed-fleet vs the occupancy
# router + telemetry autoscaler (ISSUE 14 evidence; deterministic
# SimClock arithmetic, no TPU required).  Headline: occupancy+autoscale
# beats round-robin on TTFT p99, matches it on tokens/s, and every
# scale-out reacts within one warm-pool claim latency.  Rows land in
# BENCH_r13.json; bounds asserted in tests/test_bench_infra.py.
bench-fleet:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_fleet; \
	print(json.dumps(bench_fleet(), indent=1))"

# Serving failure domain (ISSUE 15): the hardened router (ejection +
# hedging + degraded fallback) vs the no-ejection/no-hedge baseline
# under ONE seeded outage trace (fleet-wide scrape storm, single-replica
# scrape storm, replica freeze, kill-mid-decode) composed by the
# FaultInjector on the harness SimClock.  Headline: hardened serves the
# whole trace (zero dropped) with a bounded all-requests TTFT p99; the
# baseline's is unbounded (the frozen replica eats >1% of the trace).
# Rows land in BENCH_r14.json; bounds asserted in tests/test_bench_infra.py.
bench-fleet-chaos:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_fleet_chaos; \
	print(json.dumps(bench_fleet_chaos(), indent=1))"

# Request flight-recorder overhead (ISSUE 16): the fleet sim's seeded
# outage trace replayed with the per-request recorder + SLO burn engine
# off vs on, alternated best-of pairs; the seeded event log is asserted
# byte-identical between the arms inside the bench.  Contract
# (documented in bench_reqtrace's docstring): relative overhead <= 5%
# OR absolute overhead <= 150 us per request — the sim's whole
# per-request cost is ~300 us of arithmetic, so the absolute bound is
# the honest one on this baseline.  Rows land in BENCH_r15.json;
# bounds asserted in tests/test_bench_infra.py.
bench-reqtrace:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_reqtrace; \
	print(json.dumps(bench_reqtrace(), indent=1))"

# One cluster, one day (ISSUE 18): training gangs + the serving fleet
# on ONE shared node inventory through a seeded chaos day (scrape
# storm, replica freeze, kill-mid-decode, scheduler kill -9 + resync,
# node drain through the scheduler).  Headline: the hardened stack
# (shrink-before-evict + hedging + ejection) serves the whole trace
# with zero drops and returns every gang to Running with exact restart
# counters; the baseline drops requests and pays whole-gang evictions.
# Both arms run twice inside the bench and must hash identically.
# Rows land in BENCH_r16.json; bounds asserted in tests/test_bench_infra.py.
bench-cluster:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_cluster; \
	print(json.dumps(bench_cluster(), indent=1))"

# Disaggregated prefill/decode serving (ISSUE 20): a prefill fleet
# (queue-depth dispatch, prompt-only admission) handing finished
# prompts to a decode fleet (free-KV-block dispatch, block-table
# adoption) vs the unified fleet, at equal total KV blocks on the same
# accelerators, over a seeded prefill-burst trace (long-prompt bursts
# on a steady decode-heavy floor) and its steady no-burst twin.
# Headline: disaggregated TTFT p99 >= 1.5x better under the burst;
# steady tokens/s within 10% of unified.  Rows land in BENCH_r18.json;
# bounds asserted in tests/test_bench_infra.py.
bench-disagg:
	JAX_PLATFORMS=cpu python -c "import json; from bench import bench_disagg; \
	print(json.dumps(bench_disagg(), indent=1))"

docker-build:
	docker build -f build/images/tpu-training-operator/Dockerfile -t $(IMG) .

deploy:
	kubectl apply -k manifests/overlays/standalone

undeploy:
	kubectl delete -k manifests/overlays/standalone

clean:
	rm -f $(NATIVE_LIB)
